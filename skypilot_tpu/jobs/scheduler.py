"""Managed-jobs scheduler: bounded controller parallelism + queueing.

Twin of the reference's event-driven scheduler
(sky/jobs/scheduler.py:114 `maybe_schedule_next_jobs`, caps at :295-315).
Design, as there:

  * Scheduling is event-driven, not a daemon: `maybe_schedule_next_jobs`
    runs on every state transition that could free or fill a slot (job
    submit, launch finished, controller exit) and is a no-op otherwise.
  * Two separate caps:
      - LAUNCHING cap — how many controllers may be provisioning task
        clusters at once (launches are CPU/network heavy on the
        controller host).
      - ALIVE cap — how many controller processes may exist at all
        (each is a Python process; bounded by host memory).
  * All transitions happen under one inter-process file lock, so any
    number of API-server workers / exiting controllers can race on the
    schedule safely. A job's schedule_state walks
    WAITING → LAUNCHING → ALIVE → DONE; recovery relaunches re-acquire a
    launch slot via ALIVE → LAUNCHING → ALIVE.
"""
from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Dict, List, Optional

import filelock

from skypilot_tpu import sky_logging
from skypilot_tpu import state as global_state
from skypilot_tpu.jobs import fleet
from skypilot_tpu.jobs import state as jobs_state
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import ownership
from skypilot_tpu.utils import resilience
from skypilot_tpu.utils import tracing

logger = sky_logging.init_logger(__name__)

# Reference sizing: one launch ≈ 1 CPU + a controller ≈ 350 MB
# (sky/jobs/scheduler.py:295-315 computes caps from host cpu/mem).
_CONTROLLER_MEM_MB = 350


def max_launching() -> int:
    env = os.environ.get('XSKY_JOBS_MAX_LAUNCHING')
    if env:
        return max(1, int(env))
    return max(1, min(8, os.cpu_count() or 4))


def max_alive() -> int:
    env = os.environ.get('XSKY_JOBS_MAX_PARALLEL')
    if env:
        return max(1, int(env))
    try:
        pages = os.sysconf('SC_PHYS_PAGES')
        page_size = os.sysconf('SC_PAGE_SIZE')
        mem_mb = pages * page_size / (1024 * 1024)
        return max(4, int(mem_mb / _CONTROLLER_MEM_MB / 2))
    except (ValueError, OSError):
        return 16


def _lock() -> filelock.FileLock:
    path = os.path.join(
        os.path.dirname(jobs_state.db_path()), 'jobs_scheduler.lock')
    os.makedirs(os.path.dirname(path), exist_ok=True)
    return filelock.FileLock(path, timeout=30)


def schedule_lock() -> filelock.FileLock:
    """The scheduler's inter-process lock, for operations that must not
    interleave with a WAITING→LAUNCHING claim (e.g. cancel)."""
    return _lock()


def _spawn_controller(job_id: int) -> None:
    from skypilot_tpu.utils import tracing
    from skypilot_tpu.workspaces import context as ws_context
    record = jobs_state.get_job(job_id)
    env = ws_context.controller_env(
        record.get('workspace') if record else None)
    # Hand the submitting request's trace to the controller: its
    # launch/recovery spans parent back to the `jobs.launch` request
    # (a reconciler respawn has no ambient trace — the controller
    # then roots a fresh one).
    env = tracing.env_for_child(env)
    proc = subprocess.Popen(
        [sys.executable, '-m', 'skypilot_tpu.jobs.controller',
         str(job_id)],
        env=env,
        start_new_session=True,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    jobs_state.set_controller_pid(job_id, proc.pid)


def max_controller_respawns() -> int:
    return int(os.environ.get('XSKY_JOBS_MAX_CONTROLLER_RESPAWNS', '3'))


def _reconcile_dead_controllers() -> Dict[str, List]:
    """Re-exec (or, past the respawn budget, fail) jobs whose
    controllers died without cleanup.

    HA (VERDICT r3 #9; ref kubernetes-ray.yml.j2:270-366 re-execs
    controllers on pod restart): a non-terminal job whose controller
    process is gone — API-server/pod restart, OOM kill, chaos SIGKILL
    — is requeued as WAITING, so the scheduler loop starts a fresh
    controller that resumes from the persisted current_task/recovery
    state. Respawns are bounded (a controller that crashes on its own
    bug must not loop forever); past the budget the job fails and its
    cluster is reaped. Every repair lands in the recovery journal as a
    ``reconcile.*`` event. Caller must hold the scheduler lock.
    Returns ``{'respawned': [job_ids], 'orphaned': [cluster_names]}``;
    the orphaned clusters must be reaped *after* releasing the lock.
    """
    respawned: List[int] = []
    orphaned: List[str] = []
    for row in jobs_state.get_jobs():
        if row['schedule_state'] not in (jobs_state.ScheduleState.LAUNCHING,
                                         jobs_state.ScheduleState.ALIVE):
            continue
        if common_utils.pid_alive(row['controller_pid']):
            continue
        job_id = row['job_id']
        if not row['status'].is_terminal():
            if not ownership.owns(f'job/{job_id}'):
                # Multi-server sharding: a peer server owns this
                # controller's takeover; leave the whole repair
                # (respawn AND slot release) to its reconcile tick.
                continue
            if not ownership.claim_repair(f'job/{job_id}',
                                          'controller process died'):
                # Racing takeover already claimed by a peer (the yield
                # is journalled); respawning here too would mint the
                # duplicate controller the claim exists to prevent.
                continue
            respawns = jobs_state.bump_controller_respawns(job_id)
            if respawns <= max_controller_respawns():
                logger.warning(
                    f'Managed job {job_id} controller '
                    f'(pid {row["controller_pid"]}) died; re-execing '
                    f'(respawn {respawns}/{max_controller_respawns()}).')
                global_state.record_recovery_event(
                    'reconcile.controller_respawn',
                    scope=f'job/{job_id}',
                    cause='controller process died',
                    detail={'pid': row['controller_pid'] or 0,
                            'respawn': respawns})
                jobs_state.set_schedule_state(
                    job_id, jobs_state.ScheduleState.WAITING)
                respawned.append(job_id)
                continue
            jobs_state.set_status(
                job_id, jobs_state.ManagedJobStatus.FAILED_CONTROLLER,
                failure_reason=('controller died '
                                f'{respawns} times; respawn budget '
                                'exhausted'))
            global_state.record_recovery_event(
                'reconcile.respawn_budget_exhausted',
                scope=f'job/{job_id}',
                cause=f'controller died {respawns} times')
        logger.warning(
            f'Managed job {job_id} controller '
            f'(pid {row["controller_pid"]}) died without cleanup; '
            'releasing its scheduler slot.')
        jobs_state.set_schedule_state(job_id,
                                      jobs_state.ScheduleState.DONE)
        global_state.release_lease(f'job/{job_id}')
        if row['cluster_name']:
            orphaned.append(row['cluster_name'])
    return {'respawned': respawned, 'orphaned': orphaned}


def _reap_clusters(cluster_names: List[str]) -> None:
    """Best-effort teardown of task clusters orphaned by dead
    controllers (nothing else will ever down them). Each teardown is
    journalled so `xsky events` shows who reclaimed the cluster."""
    from skypilot_tpu import core as core_lib
    from skypilot_tpu import exceptions
    for name in cluster_names:
        try:
            core_lib.down(name, purge=True)
        except exceptions.ClusterDoesNotExist:
            continue
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(f'Failed to reap orphaned cluster '
                           f'{name!r}: {e}')
            continue
        global_state.record_recovery_event(
            'reconcile.orphan_teardown', scope=f'cluster/{name}',
            cause='task cluster of a dead controller')


def submit_job(job_id: int) -> None:
    """Queue a freshly added job and kick the schedule."""
    jobs_state.set_schedule_state(job_id,
                                  jobs_state.ScheduleState.WAITING)
    maybe_schedule_next_jobs()


def maybe_schedule_next_jobs() -> Dict[str, List]:
    """Start controllers for WAITING jobs while slots are free.

    Safe to call from anywhere at any time; does nothing when no slots
    or no waiting jobs. (Twin of sky/jobs/scheduler.py:114.) Returns
    the dead-controller reconcile summary (`{'respawned', 'orphaned'}`)
    for the reconciler/doctor; all other callers ignore it.
    """
    reconciled: Dict[str, List] = {'respawned': [], 'orphaned': []}
    try:
        with _lock():
            reconciled = _reconcile_dead_controllers()
            while True:
                counts = jobs_state.schedule_state_counts()
                launching = counts.get(jobs_state.ScheduleState.LAUNCHING,
                                       0)
                alive = counts.get(jobs_state.ScheduleState.ALIVE, 0)
                if launching >= max_launching():
                    break
                if launching + alive >= max_alive():
                    break
                # Fair-share admission (jobs/fleet.py): weighted shares
                # across workspaces + priority + starvation aging pick
                # the claim, not submission order.
                job_id = fleet.claim_next_waiting()
                if job_id is None:
                    break
                if not ownership.owns(f'job/{job_id}'):
                    # The shard map assigns this controller to a peer
                    # server: hand the claim back and stop this pass —
                    # the owner spawns it on its next schedule kick
                    # (bounded by its reconcile interval). Breaking,
                    # not continuing: claim_next_waiting would hand the
                    # same job straight back and spin this loop.
                    jobs_state.set_schedule_state(
                        job_id, jobs_state.ScheduleState.WAITING)
                    break
                logger.info(f'Scheduling managed job {job_id} '
                            f'(launching={launching + 1}, '
                            f'alive={alive})')
                try:
                    _spawn_controller(job_id)
                except OSError as e:
                    jobs_state.set_status(
                        job_id,
                        jobs_state.ManagedJobStatus.FAILED_CONTROLLER,
                        failure_reason=f'controller spawn failed: {e}')
                    jobs_state.set_schedule_state(
                        job_id, jobs_state.ScheduleState.DONE)
    except filelock.Timeout:
        # Another process owns the schedule; it will pick the jobs up.
        logger.debug('Scheduler lock busy; skipping tick.')
    # Outside the lock: teardown is slow and must not block scheduling.
    _reap_clusters(reconciled['orphaned'])
    return reconciled


def launch_done(job_id: int) -> None:
    """Controller finished provisioning: free the launch slot."""
    with _lock():
        jobs_state.set_schedule_state(job_id,
                                      jobs_state.ScheduleState.ALIVE)
    maybe_schedule_next_jobs()


def acquire_launch_slot(job_id: int,
                        poll_interval_s: float = 0.5,
                        timeout_s: Optional[float] = None) -> None:
    """Block until a launch slot is free, then take it (recovery path).

    An ALIVE controller that needs to relaunch its cluster must wait its
    turn behind fresh launches so a preemption storm cannot stampede the
    provisioner (reference: schedule_state WAITING→LAUNCHING round-trip
    in sky/jobs/scheduler.py).
    """
    deadline = (time.time() + timeout_s) if timeout_s else None
    wait_start = time.time()
    # Jittered backoff instead of the old fixed-interval filelock poll:
    # a preemption storm parks every recovering controller here, and N
    # controllers hammering the scheduler lock in lockstep each 0.5 s
    # starved the one holding it. Caps at 8x the base interval.
    backoff = common_utils.Backoff(initial=poll_interval_s, factor=1.5,
                                   cap=poll_interval_s * 8, jitter=0.2)
    polls = 0
    with tracing.span('fleet.queue_wait', job=job_id) as sp:
        while True:
            # A controller can queue here for a long time during a
            # preemption storm; keep its liveness lease fresh or the
            # reconciler would report a healthy-but-waiting controller
            # as expired.
            global_state.heartbeat_lease(f'job/{job_id}',
                                         owner='jobs-controller')
            acquired = False
            with _lock():
                reconciled = _reconcile_dead_controllers()
                counts = jobs_state.schedule_state_counts()
                if counts.get(jobs_state.ScheduleState.LAUNCHING,
                              0) < max_launching():
                    jobs_state.set_schedule_state(
                        job_id, jobs_state.ScheduleState.LAUNCHING)
                    acquired = True
            _reap_clusters(reconciled['orphaned'])
            if acquired:
                sp.set(polls=polls,
                       waited_s=round(time.time() - wait_start, 3))
                return
            polls += 1
            if deadline and time.time() > deadline:
                sp.set(polls=polls, outcome='timeout')
                raise TimeoutError(
                    f'No launch slot for job {job_id} after '
                    f'{timeout_s}s')
            resilience.sleep(backoff.current_backoff())


def job_done(job_id: int) -> None:
    """Controller exited: free all slots and wake the queue."""
    with _lock():
        jobs_state.set_schedule_state(job_id,
                                      jobs_state.ScheduleState.DONE)
    # Clean exit releases the liveness lease (a crash leaves it for
    # the reconciler to expire).
    global_state.release_lease(f'job/{job_id}')
    maybe_schedule_next_jobs()
