"""Generate the IBM Cloud VPC catalog CSV (twin of
sky/catalog/data_fetchers/fetch_ibm.py in role).

With credentials + egress, rows would come from the VPC
instance/profiles endpoint plus the Global Catalog pricing API;
offline (this environment) the checked-in CSV is a curated snapshot of
the GPU (gx2 = V100, gx3 = L4, gx3d = L40S) and balanced CPU profiles
at published on-demand list prices. IBM VPC Gen2 has no spot market
(SpotPrice 0 -> never offered for use_spot).

Run: python -m skypilot_tpu.catalog.data_fetchers.fetch_ibm
"""
from __future__ import annotations

import csv
import os
from typing import List, Tuple

# (profile, acc_name, acc_count, vcpus, mem_gib, acc_mem_gib, price)
_SKUS: List[Tuple[str, str, float, float, float, float, float]] = [
    ('gx2-8x64x1v100', 'V100', 1, 8, 64, 16, 2.54),
    ('gx2-16x128x1v100', 'V100', 1, 16, 128, 16, 3.06),
    ('gx2-16x128x2v100', 'V100', 2, 16, 128, 32, 5.07),
    ('gx2-32x256x2v100', 'V100', 2, 32, 256, 32, 6.12),
    ('gx3-16x80x1l4', 'L4', 1, 16, 80, 24, 1.40),
    ('gx3-32x160x2l4', 'L4', 2, 32, 160, 48, 2.80),
    ('gx3-64x320x4l4', 'L4', 4, 64, 320, 96, 5.60),
    ('gx3d-40x200x1l40s', 'L40S', 1, 40, 200, 48, 3.55),
    ('gx3d-80x400x2l40s', 'L40S', 2, 80, 400, 96, 7.10),
    # Balanced CPU profiles.
    ('bx2-4x16', '', 0, 4, 16, 0, 0.192),
    ('bx2-8x32', '', 0, 8, 32, 0, 0.384),
    ('bx2-16x64', '', 0, 16, 64, 0, 0.768),
]

# Region -> zone count (zones are {region}-1..{region}-N).
_REGIONS = {
    'us-south': 3,
    'us-east': 3,
    'eu-de': 3,
    'eu-gb': 3,
    'jp-tok': 3,
    'au-syd': 3,
}

HEADER = ['InstanceType', 'AcceleratorName', 'AcceleratorCount', 'vCPUs',
          'MemoryGiB', 'AcceleratorMemoryGiB', 'Price', 'SpotPrice',
          'Region', 'AvailabilityZone']


def rows_static() -> List[List[str]]:
    out = []
    for itype, acc, count, vcpus, mem, acc_mem, price in _SKUS:
        for region, n_zones in _REGIONS.items():
            for z in range(1, n_zones + 1):
                out.append([itype, acc, f'{count:g}', f'{vcpus:g}',
                            f'{mem:g}', f'{acc_mem:g}', f'{price:.4f}',
                            '0', region, f'{region}-{z}'])
    return out


def main() -> None:
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, 'data', 'ibm', 'catalog.csv')
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, 'w', newline='', encoding='utf-8') as f:
        writer = csv.writer(f)
        writer.writerow(HEADER)
        writer.writerows(rows_static())
    print(f'Wrote {path} (static snapshot)')


if __name__ == '__main__':
    main()
