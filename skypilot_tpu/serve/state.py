"""Serve state: services + replicas (twin of sky/serve/serve_state.py)."""
from __future__ import annotations

import enum
import json
import os
import sqlite3
import threading
import time
from typing import Any, Dict, List, Optional

_lock = threading.RLock()


class ServiceStatus(enum.Enum):
    CONTROLLER_INIT = 'CONTROLLER_INIT'
    REPLICA_INIT = 'REPLICA_INIT'
    READY = 'READY'
    SHUTTING_DOWN = 'SHUTTING_DOWN'
    FAILED = 'FAILED'
    NO_REPLICA = 'NO_REPLICA'


class ReplicaStatus(enum.Enum):
    PENDING = 'PENDING'
    PROVISIONING = 'PROVISIONING'
    STARTING = 'STARTING'
    READY = 'READY'
    NOT_READY = 'NOT_READY'
    FAILED = 'FAILED'
    PREEMPTED = 'PREEMPTED'
    SHUTTING_DOWN = 'SHUTTING_DOWN'

    def is_terminal(self) -> bool:
        """No more log output coming / cluster going away. The single
        source of truth for replica-tail stop + active-count logic —
        hand-copied lists go stale the day this enum grows."""
        return self in (ReplicaStatus.FAILED, ReplicaStatus.PREEMPTED,
                        ReplicaStatus.SHUTTING_DOWN)


def _db() -> sqlite3.Connection:
    from skypilot_tpu.utils import db_utils
    path = os.path.expanduser(
        os.environ.get('XSKY_SERVE_DB', '~/.xsky/serve.db'))
    conn = db_utils.connect(path, timeout=30, check_same_thread=False)
    conn.executescript("""
        CREATE TABLE IF NOT EXISTS services (
            name TEXT PRIMARY KEY,
            task_config TEXT,
            status TEXT,
            controller_pid INTEGER,
            lb_port INTEGER,
            created_at REAL,
            version INTEGER DEFAULT 1
        );
        CREATE TABLE IF NOT EXISTS replicas (
            service_name TEXT,
            replica_id INTEGER,
            cluster_name TEXT,
            status TEXT,
            endpoint TEXT,
            launched_at REAL,
            version INTEGER DEFAULT 1,
            PRIMARY KEY (service_name, replica_id)
        );
        CREATE TABLE IF NOT EXISTS service_metrics_history (
            service_name TEXT,
            ts REAL,
            qps REAL,
            target_replicas INTEGER,
            ready_replicas INTEGER
        );
        CREATE INDEX IF NOT EXISTS idx_metrics_history
            ON service_metrics_history (service_name, ts)""")
    for table, column in (('services', 'version INTEGER DEFAULT 1'),
                          ('replicas', 'version INTEGER DEFAULT 1'),
                          # Mixed fleets: spot replicas + on-demand
                          # fallback replicas coexist per service.
                          ('replicas', 'spot INTEGER DEFAULT 1'),
                          # Workspace isolation: serve.down/logs authz
                          # resolves service ownership from this column.
                          ('services', 'workspace TEXT'),
                          # Live metrics, written each controller tick
                          # (dashboard service detail: QPS + target).
                          ('services', 'qps REAL'),
                          ('services', 'target_replicas INTEGER'),
                          # The task's job id on the replica cluster
                          # (execution.launch return): live log tails
                          # poll it directly — one remote exec instead
                          # of a queue lookup per poll.
                          ('replicas', 'job_id INTEGER'),
                          # How the current update shifts traffic:
                          # 'rolling' (mixed old+new) or 'blue_green'
                          # (old-only until the new fleet is ready).
                          ('services', "update_mode TEXT"),
                          # HA respawn budget (reconciler): a
                          # controller that crashes on its own bug
                          # must not be re-execed every tick forever.
                          ('services',
                           'controller_respawns INTEGER DEFAULT 0'),
                          # Graceful drain: a draining replica stops
                          # admitting (LB answers 503+Retry-After for
                          # it) but keeps serving inflight requests
                          # until the drain deadline, then terminates.
                          ('replicas', 'draining INTEGER DEFAULT 0')):
        try:
            conn.execute(f'ALTER TABLE {table} ADD COLUMN {column}')
        except Exception:  # pylint: disable=broad-except
            # Column exists (sqlite / pg error classes differ). Roll
            # back so a poisoned pg transaction doesn't swallow every
            # LATER alter in this loop (jobs/state.py has the same
            # guard) — without it the services table misses columns
            # and the SELECT * unpack breaks.
            try:
                conn.rollback()
            except Exception:  # pylint: disable=broad-except
                pass
    conn.commit()
    return conn


# ---- services ----


def add_service(name: str, task_config: Dict[str, Any],
                lb_port: int, workspace: Optional[str] = None) -> None:
    """Create the service row; raises ValueError if the name is taken.

    Plain INSERT, no upsert: creation must be atomic so two concurrent
    `serve.up` calls cannot race past up()'s exists-check and the
    second silently re-home the first's service (and its workspace)
    — the loser gets the constraint error instead (code-review r5).
    """
    with _lock:
        conn = _db()
        try:
            conn.execute(
                'INSERT INTO services (name, task_config, status, '
                'lb_port, created_at, workspace) '
                'VALUES (?, ?, ?, ?, ?, ?)',
                (name, json.dumps(task_config),
                 ServiceStatus.CONTROLLER_INIT.value, lb_port,
                 time.time(), workspace))
        except Exception as e:  # pylint: disable=broad-except
            conn.rollback()
            conn.close()
            # sqlite IntegrityError / pg UniqueViolation → name taken.
            if (isinstance(e, sqlite3.IntegrityError)
                    or 'unique' in str(e).lower()
                    or 'duplicate' in str(e).lower()):
                raise ValueError(
                    f'Service {name!r} already exists.') from e
            raise
        conn.commit()
        conn.close()


def bump_service_version(name: str, task_config: Dict[str, Any],
                         mode: str = 'rolling') -> int:
    """Install a new task config as the service's next version
    (twin of sky/serve update: ReplicaInfo.version,
    sky/serve/replica_managers.py:388). Returns the new version."""
    with _lock:
        conn = _db()
        conn.execute(
            'UPDATE services SET task_config=?, version=version+1, '
            'update_mode=? WHERE name=?',
            (json.dumps(task_config), mode, name))
        conn.commit()
        row = conn.execute('SELECT version FROM services WHERE name=?',
                           (name,)).fetchone()
        conn.close()
    if row is None:
        raise ValueError(f'Service {name!r} not found.')
    return row[0]


def set_service_status(name: str, status: ServiceStatus) -> None:
    with _lock:
        conn = _db()
        conn.execute('UPDATE services SET status=? WHERE name=?',
                     (status.value, name))
        conn.commit()
        conn.close()


# Bounded per-service metrics history ring for the dashboard chart. At
# the controller's default 2 s tick (controller.py CONTROLLER_INTERVAL_S)
# 3600 rows retain the last ~2 hours; slower ticks retain
# proportionally more. Row count, not wall clock, bounds the DB.
_METRICS_HISTORY_MAX = 3600


def set_service_metrics(name: str, qps: Optional[float],
                        target_replicas: Optional[int],
                        ready_replicas: Optional[int] = None) -> None:
    """Controller-tick metrics snapshot (serve.status / dashboard).

    Besides the live columns on the services row, each tick appends to
    a bounded `service_metrics_history` ring so the dashboard can chart
    the trend (`serve.history` verb), not just the instant."""
    with _lock:
        conn = _db()
        conn.execute(
            'UPDATE services SET qps=?, target_replicas=? WHERE name=?',
            (qps, target_replicas, name))
        conn.execute(
            'INSERT INTO service_metrics_history '
            '(service_name, ts, qps, target_replicas, ready_replicas) '
            'VALUES (?, ?, ?, ?, ?)',
            (name, time.time(), qps, target_replicas, ready_replicas))
        conn.execute(
            'DELETE FROM service_metrics_history WHERE service_name=? '
            'AND ts NOT IN (SELECT ts FROM service_metrics_history '
            'WHERE service_name=? ORDER BY ts DESC LIMIT ?)',
            (name, name, _METRICS_HISTORY_MAX))
        conn.commit()
        conn.close()


def get_metrics_history(name: str,
                        limit: int = 720) -> List[Dict[str, Any]]:
    """Most recent `limit` ticks, oldest first (chart-ready)."""
    with _lock:
        conn = _db()
        rows = conn.execute(
            'SELECT ts, qps, target_replicas, ready_replicas FROM '
            'service_metrics_history WHERE service_name=? '
            'ORDER BY ts DESC LIMIT ?', (name, int(limit))).fetchall()
        conn.close()
    return [{'ts': r[0], 'qps': r[1], 'target_replicas': r[2],
             'ready_replicas': r[3]} for r in reversed(rows)]


def set_service_controller_pid(name: str, pid: int) -> None:
    with _lock:
        conn = _db()
        conn.execute('UPDATE services SET controller_pid=? WHERE name=?',
                     (pid, name))
        conn.commit()
        conn.close()


def bump_controller_respawns(name: str) -> int:
    with _lock:
        conn = _db()
        conn.execute(
            'UPDATE services SET '
            'controller_respawns=COALESCE(controller_respawns, 0)+1 '
            'WHERE name=?', (name,))
        conn.commit()
        row = conn.execute(
            'SELECT controller_respawns FROM services WHERE name=?',
            (name,)).fetchone()
        conn.close()
    return row[0] if row else 0


def reset_controller_respawns(name: str) -> None:
    """The respawn budget bounds crash LOOPS, not lifetime restarts: a
    respawned controller that reaches steady state (READY) resets it,
    matching the managed-jobs budget semantics."""
    with _lock:
        conn = _db()
        conn.execute(
            'UPDATE services SET controller_respawns=0 WHERE name=?',
            (name,))
        conn.commit()
        conn.close()


def get_service(name: str) -> Optional[Dict[str, Any]]:
    with _lock:
        conn = _db()
        row = conn.execute('SELECT * FROM services WHERE name=?',
                           (name,)).fetchone()
        conn.close()
    return _service_dict(row) if row else None


def get_services(names: Optional[List[str]] = None,
                 limit: Optional[int] = None,
                 offset: int = 0) -> List[Dict[str, Any]]:
    """Service records, stable name order; the name filter pushes into
    SQL so a point `serve status NAME` never scans the fleet."""
    from skypilot_tpu.utils import db_utils
    if names and len(names) > db_utils.MAX_NAME_PUSHDOWN:
        # Same host-parameter-cap fallback as state.get_clusters.
        name_set = set(names)
        return db_utils.page_rows(
            [s for s in get_services() if s['name'] in name_set],
            limit, offset)
    query, args = 'SELECT * FROM services', []
    if names:
        query += f" WHERE name IN ({','.join('?' * len(names))})"
        args += list(names)
    query += ' ORDER BY name' + db_utils.page_sql(limit, offset)
    with _lock:
        conn = _db()
        rows = conn.execute(query, args).fetchall()
        conn.close()
    return [_service_dict(r) for r in rows]


def remove_service(name: str) -> None:
    with _lock:
        conn = _db()
        conn.execute('DELETE FROM services WHERE name=?', (name,))
        conn.execute('DELETE FROM replicas WHERE service_name=?', (name,))
        conn.execute('DELETE FROM service_metrics_history '
                     'WHERE service_name=?', (name,))
        conn.commit()
        conn.close()


def _service_dict(row) -> Dict[str, Any]:
    (name, task_config, status, pid, lb_port, created_at, version,
     workspace, qps, target_replicas, update_mode,
     controller_respawns) = row
    return {
        'name': name,
        'task_config': json.loads(task_config or '{}'),
        'status': ServiceStatus(status),
        'controller_pid': pid,
        'lb_port': lb_port,
        'created_at': created_at,
        'version': version or 1,
        'workspace': workspace,
        'qps': qps,
        'target_replicas': target_replicas,
        'update_mode': update_mode or 'rolling',
        'controller_respawns': controller_respawns or 0,
    }


# ---- replicas ----


def upsert_replica(service_name: str, replica_id: int, cluster_name: str,
                   status: ReplicaStatus,
                   endpoint: Optional[str] = None,
                   version: int = 1,
                   spot: bool = True,
                   job_id: Optional[int] = None) -> None:
    with _lock:
        conn = _db()
        conn.execute(
            'INSERT INTO replicas (service_name, replica_id, cluster_name,'
            ' status, endpoint, launched_at, version, spot, job_id) '
            'VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?) '
            'ON CONFLICT(service_name, replica_id) DO UPDATE SET '
            'status=excluded.status, '
            'endpoint=COALESCE(excluded.endpoint, replicas.endpoint), '
            'job_id=COALESCE(excluded.job_id, replicas.job_id)',
            (service_name, replica_id, cluster_name, status.value,
             endpoint, time.time(), version, int(spot), job_id))
        conn.commit()
        conn.close()


def set_replica_draining(service_name: str, replica_id: int,
                         draining: bool = True) -> None:
    """Flip the replica's drain flag (graceful drain: stop admitting,
    finish inflight, then terminate). The controller's serving set and
    the LB's draining set both derive from this column."""
    with _lock:
        conn = _db()
        conn.execute(
            'UPDATE replicas SET draining=? WHERE service_name=? AND '
            'replica_id=?', (int(draining), service_name, replica_id))
        conn.commit()
        conn.close()


def remove_replica(service_name: str, replica_id: int) -> None:
    with _lock:
        conn = _db()
        conn.execute(
            'DELETE FROM replicas WHERE service_name=? AND replica_id=?',
            (service_name, replica_id))
        conn.commit()
        conn.close()


def get_replicas(service_name: str) -> List[Dict[str, Any]]:
    with _lock:
        conn = _db()
        rows = conn.execute(
            'SELECT * FROM replicas WHERE service_name=? '
            'ORDER BY replica_id', (service_name,)).fetchall()
        conn.close()
    return [{
        'service_name': r[0],
        'replica_id': r[1],
        'cluster_name': r[2],
        'status': ReplicaStatus(r[3]),
        'endpoint': r[4],
        'launched_at': r[5],
        'version': r[6] or 1,
        'spot': bool(r[7]) if len(r) > 7 and r[7] is not None else True,
        'job_id': r[8] if len(r) > 8 else None,
        'draining': bool(r[9]) if len(r) > 9 and r[9] is not None
                    else False,
    } for r in rows]
