"""Prometheus-format metrics for the API server.

Twin of sky/server/metrics.py:19-35 (prometheus_client counters +
histograms on every endpoint) — rendered by hand in the text exposition
format so the stdlib-only server stays dependency-free.

Exposed at GET /metrics:
  * xsky_http_requests_total{path,code}
  * xsky_requests_total{verb,status}          (executor verbs)
  * xsky_request_duration_seconds{verb}       (histogram)
"""
from __future__ import annotations

import threading
from typing import Dict, List, Tuple

_lock = threading.Lock()

_http_requests: Dict[Tuple[str, int], int] = {}
_verb_requests: Dict[Tuple[str, str], int] = {}
_BUCKETS = (0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0, float('inf'))
_verb_duration_buckets: Dict[str, List[int]] = {}
_verb_duration_sum: Dict[str, float] = {}
_verb_duration_count: Dict[str, int] = {}


# Known routes; anything else buckets under '<other>' so scanners can't
# grow label cardinality without bound (or corrupt the exposition with
# quotes/newlines in the path).
_KNOWN_PATHS = frozenset({
    '/health', '/metrics', '/', '/dashboard', '/dashboard/',
    '/api/get', '/api/requests', '/api/cancel', '/tunnel',
})


def _normalize_path(path: str) -> str:
    if path in _KNOWN_PATHS:
        return path
    if path.startswith('/api/'):
        # Only verbs the payload registry knows; scanning /api/aaaN
        # must not mint new label values.
        from skypilot_tpu.server import payloads
        if payloads.known_verb(path[5:]):
            return path
    return '<other>'


def _escape_label(value: str) -> str:
    return value.replace('\\', r'\\').replace('"', r'\"').replace(
        '\n', r'\n')


def observe_http(path: str, code: int) -> None:
    """Count one HTTP request (path should be the route, not raw URL)."""
    with _lock:
        key = (_normalize_path(path), code)
        _http_requests[key] = _http_requests.get(key, 0) + 1


def observe_request(verb: str, status: str, duration_s: float) -> None:
    """Count one executor request with its end-to-end duration."""
    with _lock:
        key = (verb, status)
        _verb_requests[key] = _verb_requests.get(key, 0) + 1
        buckets = _verb_duration_buckets.setdefault(
            verb, [0] * len(_BUCKETS))
        for i, le in enumerate(_BUCKETS):
            if duration_s <= le:
                buckets[i] += 1
        _verb_duration_sum[verb] = (
            _verb_duration_sum.get(verb, 0.0) + duration_s)
        _verb_duration_count[verb] = (
            _verb_duration_count.get(verb, 0) + 1)


def reset_for_test() -> None:
    with _lock:
        _http_requests.clear()
        _verb_requests.clear()
        _verb_duration_buckets.clear()
        _verb_duration_sum.clear()
        _verb_duration_count.clear()


def _fmt_le(le: float) -> str:
    return '+Inf' if le == float('inf') else f'{le:g}'


def render() -> str:
    """Text exposition format (version 0.0.4)."""
    with _lock:
        lines = [
            '# HELP xsky_http_requests_total HTTP requests by route/code.',
            '# TYPE xsky_http_requests_total counter',
        ]
        for (path, code), n in sorted(_http_requests.items()):
            lines.append(
                f'xsky_http_requests_total{{path="{_escape_label(path)}",'
                f'code="{code}"}} {n}')
        lines += [
            '# HELP xsky_requests_total Executor requests by verb/status.',
            '# TYPE xsky_requests_total counter',
        ]
        for (verb, status), n in sorted(_verb_requests.items()):
            lines.append(
                f'xsky_requests_total{{verb="{_escape_label(verb)}",'
                f'status="{status}"}} {n}')
        lines += [
            '# HELP xsky_request_duration_seconds Executor request '
            'duration.',
            '# TYPE xsky_request_duration_seconds histogram',
        ]
        for verb in sorted(_verb_duration_buckets):
            for i, le in enumerate(_BUCKETS):
                lines.append(
                    f'xsky_request_duration_seconds_bucket{{verb="{verb}"'
                    f',le="{_fmt_le(le)}"}} '
                    f'{_verb_duration_buckets[verb][i]}')
            lines.append(
                f'xsky_request_duration_seconds_sum{{verb="{verb}"}} '
                f'{_verb_duration_sum[verb]:.6f}')
            lines.append(
                f'xsky_request_duration_seconds_count{{verb="{verb}"}} '
                f'{_verb_duration_count[verb]}')
        return '\n'.join(lines) + '\n'
