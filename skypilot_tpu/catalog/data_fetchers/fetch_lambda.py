"""Generate the Lambda Cloud catalog CSV (twin of
sky/catalog/data_fetchers/fetch_lambda_cloud.py).

With a $LAMBDA_API_KEY and egress, rows come live from
`GET /api/v1/instance-types` (price_cents_per_hour + specs per type);
offline (this environment) the checked-in CSV is generated from a
static snapshot of Lambda's published on-demand price sheet. Lambda has
no spot market (SpotPrice 0 → never offered for use_spot) and flat
regions (the pseudo-zone equals the region).

Run: python -m skypilot_tpu.catalog.data_fetchers.fetch_lambda
"""
from __future__ import annotations

import csv
import os
from typing import List, Tuple

# (instance_type, acc_name, acc_count, vcpus, mem_gib, acc_mem_gib, price)
_SKUS: List[Tuple[str, str, float, float, float, float, float]] = [
    ('gpu_1x_a10', 'A10', 1, 30, 200, 24, 0.75),
    ('gpu_1x_a100_sxm4', 'A100', 1, 30, 200, 40, 1.29),
    ('gpu_8x_a100_80gb_sxm4', 'A100-80GB', 8, 240, 1800, 640, 14.32),
    ('gpu_1x_h100_pcie', 'H100', 1, 26, 200, 80, 2.49),
    ('gpu_8x_h100_sxm5', 'H100', 8, 208, 1800, 640, 23.92),
    ('gpu_1x_rtx6000', 'RTX6000', 1, 14, 46, 24, 0.50),
    ('cpu_4x_general', '', 0, 4, 16, 0, 0.10),
]

_REGIONS = ['us-east-1', 'us-west-1', 'us-south-1', 'europe-central-1',
            'asia-pacific-1']

HEADER = ['InstanceType', 'AcceleratorName', 'AcceleratorCount', 'vCPUs',
          'MemoryGiB', 'AcceleratorMemoryGiB', 'Price', 'SpotPrice',
          'Region', 'AvailabilityZone']


def rows_from_api() -> List[List[str]]:
    """Live rows from /instance-types (requires key + egress)."""
    from skypilot_tpu.provision.lambda_cloud import rest
    reply = rest.Transport().call('GET', '/instance-types')
    out = []
    for name, entry in sorted(reply.get('data', {}).items()):
        itype = entry.get('instance_type', {})
        specs = itype.get('specs', {})
        price = itype.get('price_cents_per_hour', 0) / 100.0
        gpus = float(specs.get('gpus', 0))
        acc = ''
        if gpus and '_' in name:
            # gpu_8x_a100_80gb_sxm4 → A100-80GB
            parts = name.split('_')[2:]
            acc = parts[0].upper()
            if len(parts) > 1 and parts[1].endswith('gb'):
                acc = f'{acc}-{parts[1].upper()}'
        regions = [r['name']
                   for r in entry.get('regions_with_capacity_available',
                                      [])] or _REGIONS
        for region in regions:
            out.append([name, acc, f'{gpus:g}',
                        f"{specs.get('vcpus', 0):g}",
                        f"{specs.get('memory_gib', 0):g}", '0',
                        f'{price:.4f}', '0', region, region])
    return out


def rows_static() -> List[List[str]]:
    out = []
    for itype, acc, count, vcpus, mem, acc_mem, price in _SKUS:
        for region in _REGIONS:
            out.append([itype, acc, f'{count:g}', f'{vcpus:g}',
                        f'{mem:g}', f'{acc_mem:g}', f'{price:.4f}', '0',
                        region, region])
    return out


def main() -> None:
    try:
        data = rows_from_api()
        source = 'live API'
    except Exception:  # pylint: disable=broad-except
        data = rows_static()
        source = 'static snapshot'
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, 'data', 'lambda', 'catalog.csv')
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, 'w', newline='', encoding='utf-8') as f:
        writer = csv.writer(f)
        writer.writerow(HEADER)
        writer.writerows(data)
    print(f'Wrote {path} ({source})')


if __name__ == '__main__':
    main()
