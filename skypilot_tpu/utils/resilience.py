"""Unified retry/deadline helpers for every recovery path.

Before this module each recovery path hand-rolled its own
``time.sleep``/attempt-counter loop (REST 429 backoff in the provider
transports, probe tolerance in the jobs controller, readiness probes in
serve). They all reduce to the same three primitives:

  * :class:`Deadline` — a remaining-time budget that propagates across
    layers (``deadline.sub(10)`` hands a callee at most 10s *and* never
    more than the caller has left);
  * ``common_utils.Backoff`` — capped exponential backoff, optionally
    jittered (deterministic when seeded, for tests);
  * :func:`retry_transient` — retry a callable on *typed* transient
    failures only, under an attempt cap, a backoff, a deadline, and an
    optional early give-up predicate.

Instrumented modules route their cadence sleeps through
:func:`sleep` — one choke point, so the no-raw-``time.sleep``-in-retry-
loops lint (tests/unit_tests/test_chaos.py) stays a simple AST check.
"""
from __future__ import annotations

import math
import time
from typing import Any, Callable, Optional, Tuple, Type

from skypilot_tpu.utils import common_utils


class DeadlineExceeded(Exception):
    """A Deadline's budget ran out."""


class TransientError(Exception):
    """Marker for failures worth retrying (rate limits, transport drops,
    empty probe replies). Raise it (or subclass it) inside a callable
    passed to :func:`retry_transient`."""


# HTTP statuses every provider transport treats as transient.
TRANSIENT_HTTP_STATUSES = frozenset({408, 429, 500, 502, 503, 504})

DEFAULT_TRANSIENT_TYPES: Tuple[Type[BaseException], ...] = (
    TransientError, ConnectionError, TimeoutError, InterruptedError)


class Deadline:
    """Monotonic remaining-time budget.

    ``Deadline(30)`` expires 30s from now; ``Deadline.unlimited()`` never
    does. Pass deadlines *down* — a callee that needs its own cap takes
    ``deadline.sub(cap)`` so it can never outlive its caller's budget.
    """

    def __init__(self, budget_s: Optional[float]) -> None:
        self._expires_at = (None if budget_s is None
                            else time.monotonic() + budget_s)

    @classmethod
    def unlimited(cls) -> 'Deadline':
        return cls(None)

    @property
    def bounded(self) -> bool:
        return self._expires_at is not None

    def remaining(self) -> float:
        if self._expires_at is None:
            return math.inf
        return max(0.0, self._expires_at - time.monotonic())

    @property
    def expired(self) -> bool:
        return self._expires_at is not None and \
            time.monotonic() >= self._expires_at

    def sub(self, budget_s: float) -> 'Deadline':
        """Child budget: at most `budget_s`, never more than remains."""
        return Deadline(min(budget_s, self.remaining()))

    def check(self, what: str = 'operation') -> None:
        if self.expired:
            raise DeadlineExceeded(f'{what} exceeded its deadline.')

    def sleep(self, seconds: float) -> bool:
        """Sleep up to `seconds`, capped at the remaining budget.
        Returns False (without sleeping) when the budget is exhausted."""
        if self.expired:
            return False
        time.sleep(min(seconds, self.remaining()))
        return True


def sleep(seconds: float, deadline: Optional[Deadline] = None) -> bool:
    """Cadence sleep for instrumented recovery loops.

    Equivalent to ``time.sleep`` (optionally deadline-capped) but gives
    poll loops one auditable entry point instead of scattered raw
    sleeps.
    """
    if deadline is not None:
        return deadline.sleep(seconds)
    time.sleep(seconds)
    return True


def retry_transient(
        fn: Callable[[], Any],
        *,
        max_attempts: int = 3,
        backoff: Optional[common_utils.Backoff] = None,
        deadline: Optional[Deadline] = None,
        transient: Tuple[Type[BaseException], ...] = DEFAULT_TRANSIENT_TYPES,
        give_up: Optional[Callable[[], bool]] = None,
        on_retry: Optional[Callable[[int, BaseException], None]] = None
) -> Any:
    """Call `fn`, retrying typed-transient failures with backoff.

    Only exceptions in `transient` are retried — anything else
    propagates immediately (a permission error must not burn a retry
    budget). Retrying stops when attempts run out, the deadline budget
    is spent, or `give_up()` turns True (checked after each failure —
    e.g. "the cloud no longer reports the cluster alive, stop probing");
    the last transient error is re-raised.
    """
    assert max_attempts >= 1, max_attempts
    backoff = backoff or common_utils.Backoff(
        initial=0.5, cap=10.0, jitter=0.2)
    deadline = deadline or Deadline.unlimited()
    last_error: Optional[BaseException] = None
    for attempt in range(1, max_attempts + 1):
        try:
            return fn()
        except transient as e:  # pylint: disable=catching-non-exception
            last_error = e
            if attempt >= max_attempts:
                break
            if give_up is not None and give_up():
                break
            if on_retry is not None:
                on_retry(attempt, e)
            if not deadline.sleep(backoff.current_backoff()) or \
                    deadline.expired:
                # Budget spent (possibly by the capped sleep we just
                # took): do not start another full attempt past it.
                break
    assert last_error is not None
    raise last_error
