"""In-memory 'fake' cloud for tests (credential-free, always enabled).

Backed by the deterministic catalog in
catalog/data_fetchers/fetch_fake.py and the in-memory provisioner in
provision/fake/. Together they play the role of moto in the reference's
failover tests (tests/test_failover.py:34-60).
"""
from __future__ import annotations

import typing
from typing import Any, Dict, Optional, Tuple

from skypilot_tpu.clouds import catalog_cloud
from skypilot_tpu.utils import registry

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib


@registry.CLOUD_REGISTRY.register()
class Fake(catalog_cloud.CatalogCloud):
    _REPR = 'Fake'

    def make_deploy_resources_variables(
            self, resources: 'resources_lib.Resources', cluster_name: str,
            region: str, zone: Optional[str]) -> Dict[str, Any]:
        vars: Dict[str, Any] = {
            'cluster_name': cluster_name,
            'region': region,
            'zone': zone,
            'instance_type': resources.instance_type,
            'use_spot': resources.use_spot,
        }
        topo = self.tpu_topology_of(resources)
        if topo is not None:
            vars.update({
                'tpu_vm': True,
                'tpu_num_hosts': topo.num_hosts,
                'tpu_chips_per_host': topo.chips_per_host,
                'tpu_num_slices': topo.num_slices,
            })
            args = resources.accelerator_args or {}
            # Mirror the GCP capacity-model threading so failover walks
            # (reserved → spot → on-demand) are testable on the fake.
            vars['provisioning_model'] = \
                resources.effective_provisioning_model()
            if args.get('reservation'):
                vars['reservation'] = args['reservation']
        if resources.volumes:
            vars['volumes'] = [dict(v) for v in resources.volumes]
        return vars

    def provider_config_overrides(
            self, node_config: Dict[str, Any]) -> Dict[str, Any]:
        # Same threading as GCP: get_cluster_info builds mount commands
        # from the persisted provider_config.
        if node_config.get('volumes'):
            return {'volumes': node_config['volumes']}
        return {}

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        return True, None
