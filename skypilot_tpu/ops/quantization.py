"""Int8 weight-only quantization for serving.

Role-twin of the reference's serving quantization (the v6e serving
recipe quantizes weights to fit + feed the chip; cf. JetStream-class
engines), designed TPU-first: weights are stored int8 with
per-output-channel fp32 scales and dequantized INSIDE the consuming
matmul — `(x @ w_q.astype(bf16)) * scale` — which XLA fuses into the
matmul epilogue. Decode is HBM-bandwidth-bound, so halving the bytes
per weight read is a direct step-time win, and an 8B model's weights
(16 GB bf16) fit a single 16 GB chip at int8.

Design notes:
  * `QuantizedTensor` is a registered pytree: it flows through jit,
    `lax.scan` (leading-axis slices of both q and scale stay paired),
    and device_put without special cases.
  * The contraction axis is static aux data, counted FROM THE END so a
    stacked `[L, in, out]` weight stays valid after scan slices it to
    `[in, out]`.
  * `matmul`/`embed_rows`/`tied_head`/`expert_einsum` dispatch on
    type: plain arrays pass through untouched, so training code paths
    share the same call sites at zero cost.
  * Scales are fp32 `max(|w|)/127` per output channel — symmetric,
    zero-point-free, which keeps the dequant a single fused multiply.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class QuantizedTensor:
    """int8 values + per-output-channel fp32 scales.

    `axis` is the CONTRACTION axis as a negative index; `scale` has
    the shape of `q` with that axis removed.
    """
    q: jax.Array
    scale: jax.Array
    axis: int = -2

    def tree_flatten(self):
        return (self.q, self.scale), self.axis

    @classmethod
    def tree_unflatten(cls, axis, children):
        q, scale = children
        return cls(q, scale, axis)

    @property
    def shape(self):
        return self.q.shape

    @property
    def ndim(self) -> int:
        return self.q.ndim

    @property
    def dtype(self):
        return self.q.dtype

    @property
    def nbytes(self) -> int:
        return self.q.nbytes + self.scale.nbytes


def quantize(w: jax.Array, axis: int = -2) -> QuantizedTensor:
    """Symmetric per-output-channel int8 over the contraction `axis`."""
    if axis >= 0:
        axis = axis - w.ndim
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axis)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.round(w.astype(jnp.float32) /
                  jnp.expand_dims(scale, axis)).astype(jnp.int8)
    return QuantizedTensor(q, scale, axis)


def dequantize(w: QuantizedTensor, dtype=jnp.bfloat16) -> jax.Array:
    return (w.q.astype(jnp.float32) *
            jnp.expand_dims(w.scale, w.axis)).astype(dtype)


def matmul(x: jax.Array, w, preferred_element_type=None) -> jax.Array:
    """`x @ w` for `w` either a plain `[.., in, out]` array or a
    QuantizedTensor with contraction at -2; dequant fuses into the
    matmul epilogue."""
    if isinstance(w, QuantizedTensor):
        assert w.axis == -2, (
            f'matmul needs contraction at -2, got {w.axis}')
        out = jnp.matmul(x, w.q.astype(x.dtype),
                         preferred_element_type=preferred_element_type)
        return out * w.scale.astype(out.dtype)
    return jnp.matmul(x, w, preferred_element_type=preferred_element_type)


def embed_rows(table, tokens: jax.Array) -> jax.Array:
    """`table[tokens]` for a plain or row-quantized (axis=-1) table."""
    if isinstance(table, QuantizedTensor):
        assert table.axis == -1, (
            f'embed_rows needs per-row scales (axis -1), got {table.axis}')
        rows = table.q[tokens]
        return rows.astype(table.scale.dtype) * table.scale[tokens][..., None]
    return table[tokens]


def tied_head(hidden: jax.Array, table,
              preferred_element_type=jnp.float32) -> jax.Array:
    """`einsum('...d,vd->...v')` against a (possibly row-quantized)
    embedding table used as a tied LM head (gemma)."""
    if isinstance(table, QuantizedTensor):
        assert table.axis == -1
        out = jnp.einsum('...d,vd->...v', hidden,
                         table.q.astype(hidden.dtype),
                         preferred_element_type=preferred_element_type)
        return out * table.scale.astype(out.dtype)
    return jnp.einsum('...d,vd->...v', hidden, table,
                      preferred_element_type=preferred_element_type)


def expert_einsum(spec: str, x: jax.Array, w,
                  preferred_element_type=None) -> jax.Array:
    """MoE expert einsum (`ecd,edf->ecf` / `ecf,efd->ecd`) where `w`
    may be quantized over its middle (contraction) axis: the [E, out]
    scale broadcasts as [E, 1, out] over the `e?out` result."""
    if isinstance(w, QuantizedTensor):
        assert w.axis == -2
        out = jnp.einsum(spec, x, w.q.astype(x.dtype),
                         preferred_element_type=preferred_element_type)
        return out * w.scale[:, None, :].astype(out.dtype)
    return jnp.einsum(spec, x, w,
                      preferred_element_type=preferred_element_type)


# Weight leaves quantized for serving, keyed by name. Contraction is
# -2 (matmul convention) except the embedding table, whose rows must
# dequantize independently for the token gather (and whose tied-head
# use contracts over d = its LAST axis — the same per-row scale
# serves both).
_QUANT_AXES = {
    'wq': -2, 'wk': -2, 'wv': -2, 'wo': -2,
    'w_gate': -2, 'w_up': -2, 'w_down': -2,
    'lm_head': -2,
    'embed': -1,
}


def quantize_params(params: Params) -> Params:
    """Quantize a family's weight matrices for serving.

    Norm vectors (and any leaf not in the known weight set) stay in
    their original dtype; already-quantized leaves pass through, so
    the transform is idempotent.
    """

    def walk(node):
        if isinstance(node, dict):
            out = {}
            for key, value in node.items():
                if isinstance(value, dict):
                    out[key] = walk(value)
                elif isinstance(value, QuantizedTensor):
                    out[key] = value
                elif key in _QUANT_AXES and value.ndim >= 2:
                    out[key] = quantize(value, _QUANT_AXES[key])
                else:
                    out[key] = value
            return out
        return node

    return walk(params)


def params_nbytes(params: Params) -> int:
    return sum(leaf.nbytes
               for leaf in jax.tree_util.tree_leaves(params))


def synthetic_quantized_params(shapes: Params, key: jax.Array) -> Params:
    """Random params born directly in quantized form.

    For throughput benchmarks of models whose bf16 init would not fit
    the chip (an 8B is 16 GB bf16 — exactly one v5e's HBM before
    quantizing): weights are sampled straight as int8 with fan-in
    scales, never materializing the full-precision tree. `shapes` is
    the `jax.eval_shape` of the family's `init`.
    """

    def walk(node, key):
        if isinstance(node, dict):
            out = {}
            for name, value in sorted(node.items()):
                key, sub = jax.random.split(key)
                if isinstance(value, dict):
                    out[name] = walk(value, sub)
                elif name in _QUANT_AXES and value.ndim >= 2:
                    axis = _QUANT_AXES[name]
                    # bits+bitcast, NOT randint: eager randint would
                    # materialize a 4x int32 transient per leaf (7.5 GB
                    # for an 8B's stacked w_gate) — defeating the whole
                    # point of sampling straight into int8.
                    q = jax.lax.bitcast_convert_type(
                        jax.random.bits(sub, value.shape, jnp.uint8),
                        jnp.int8)
                    fan_in = value.shape[axis]
                    scale_shape = list(value.shape)
                    del scale_shape[axis % value.ndim]
                    scale = jnp.full(scale_shape,
                                     (fan_in ** -0.5) / 127.0,
                                     jnp.float32)
                    out[name] = QuantizedTensor(q, scale, axis)
                else:
                    out[name] = jnp.ones(value.shape, value.dtype)
            return out
        return node

    return walk(shapes, key)
