"""User management + authentication (twin of sky/users/{server,permission}).

Passwords are stored as PBKDF2-HMAC-SHA256 (100k rounds, per-user salt).
Authentication is opt-in: the API server enforces it only when
XSKY_REQUIRE_AUTH=1 (local single-user deployments stay frictionless,
like the reference's local API server).
"""
from __future__ import annotations

import base64
import hashlib
import hmac
import os
import secrets
from typing import Any, Dict, List, Optional

from skypilot_tpu import state
from skypilot_tpu.users import rbac

_PBKDF2_ROUNDS = 100_000


def _hash_password(password: str, salt: str) -> str:
    digest = hashlib.pbkdf2_hmac('sha256', password.encode(),
                                 bytes.fromhex(salt), _PBKDF2_ROUNDS)
    return digest.hex()


def create_user(name: str, password: str,
                role: str = rbac.USER_ROLE) -> Dict[str, Any]:
    if role not in rbac.ROLES:
        raise ValueError(f'Unknown role {role!r}; expected one of '
                         f'{rbac.ROLES}.')
    if not name or '\n' in name or ':' in name:
        raise ValueError(f'Invalid user name {name!r}.')
    salt = secrets.token_hex(16)
    state.add_user(name, _hash_password(password, salt), salt, role)
    return {'name': name, 'role': role}


def delete_user(name: str) -> Dict[str, Any]:
    deleted = state.delete_user(name)
    if deleted:
        # Bearer tokens die with the account.
        state.delete_api_tokens_for_user(name)
    return {'deleted': deleted}


def list_users() -> List[Dict[str, Any]]:
    return state.list_users()


def set_role(name: str, role: str) -> Dict[str, Any]:
    if role not in rbac.ROLES:
        raise ValueError(f'Unknown role {role!r}.')
    return {'updated': state.set_user_role(name, role)}


def verify_password(name: str, password: str) -> Optional[Dict[str, Any]]:
    """→ user record if the password matches, else None (constant-time
    compare)."""
    user = state.get_user(name)
    if user is None or not user.get('salt'):
        return None
    expected = user['password_hash']
    actual = _hash_password(password, user['salt'])
    if hmac.compare_digest(expected, actual):
        return user
    return None


def auth_required() -> bool:
    return os.environ.get('XSKY_REQUIRE_AUTH', '') == '1'


def authenticate_basic(header: Optional[str]) -> Optional[Dict[str, Any]]:
    """Parse an `Authorization: Basic ...` header → user record or None."""
    if not header or not header.startswith('Basic '):
        return None
    try:
        decoded = base64.b64decode(header[len('Basic '):]).decode()
        name, _, password = decoded.partition(':')
    except Exception:  # pylint: disable=broad-except
        return None
    return verify_password(name, password)


_TOKEN_PREFIX = 'xsky_'


def _hash_token(token: str) -> str:
    return hashlib.sha256(token.encode()).hexdigest()


def create_token(name: str, label: str = 'default') -> Dict[str, Any]:
    """Mint a bearer token for `name` (twin of the reference's
    service-account token auth, sky/server/server.py:176-296).

    The plaintext is returned exactly once; only its SHA-256 lands in
    the DB. Label must be unique per user (revocation handle).
    """
    if state.get_user(name) is None:
        raise ValueError(f'Unknown user {name!r}.')
    if any(t['label'] == label for t in state.list_api_tokens(name)):
        raise ValueError(
            f'User {name!r} already has a token labeled {label!r}; '
            'revoke it first.')
    token = _TOKEN_PREFIX + secrets.token_urlsafe(32)
    state.add_api_token(_hash_token(token), name, label)
    return {'name': name, 'label': label, 'token': token}


def list_tokens(name: Optional[str] = None) -> List[Dict[str, Any]]:
    return state.list_api_tokens(name)


def revoke_token(name: str, label: str) -> Dict[str, Any]:
    return {'revoked': state.delete_api_token(name, label)}


def authenticate_bearer(header: Optional[str]) -> Optional[Dict[str, Any]]:
    """Parse `Authorization: Bearer ...` → user record or None.

    `xsky_...` tokens are in-tree API tokens; anything else is treated
    as an OAuth access token when OAuth is configured (validated
    against the IdP's userinfo endpoint; users auto-provision on first
    sight — twin of the reference's OAuth middleware identity headers,
    sky/server/server.py:176-296).
    """
    if not header or not header.startswith('Bearer '):
        return None
    token = header[len('Bearer '):].strip()
    if not token.startswith(_TOKEN_PREFIX):
        return _authenticate_oauth(token)
    record = state.get_api_token(_hash_token(token))
    if record is None:
        return None
    user = state.get_user(record['user_name'])
    if user is None:
        # Deleted user: the token must die with the account.
        return None
    return user


def _oauth_subject_marker(sub: str) -> str:
    return f'oauth-sub:{sub}'


def _authenticate_oauth(token: str) -> Optional[Dict[str, Any]]:
    from skypilot_tpu.users import oauth
    if not oauth.enabled():
        return None
    try:
        info = oauth.validate_access_token(token)
    except oauth.OAuthError as e:
        from skypilot_tpu import sky_logging
        sky_logging.init_logger(__name__).warning(
            f'OAuth validation unavailable: {e}')
        return None
    if info is None or not info.get('sub'):
        return None
    user = state.get_user(info['name'])
    if user is None:
        # First sight of an IdP-verified identity: auto-provision with
        # the default role and no local password (OAuth-only account).
        # The stable OIDC `sub` is recorded as the account's identity
        # binding — preferred_username/email are display names, not
        # identifiers (OIDC core §5.7).
        state.add_user(info['name'],
                       _oauth_subject_marker(info['sub']), '',
                       rbac.USER_ROLE)
        return state.get_user(info['name'])
    if user.get('salt'):
        # Name collision with a LOCAL (password) account — e.g. an IdP
        # user who self-registered the username 'admin'. Never let an
        # OAuth identity assume a local account.
        return None
    if user.get('password_hash') != _oauth_subject_marker(info['sub']):
        # Same display name, different IdP subject: not the same
        # principal.
        return None
    return user


def authenticate(header: Optional[str]) -> Optional[Dict[str, Any]]:
    """Basic password or Bearer token, whichever the header carries."""
    if header and header.startswith('Bearer '):
        return authenticate_bearer(header)
    return authenticate_basic(header)


def bootstrap_admin_if_empty() -> None:
    """First boot with auth on: create admin with a generated password
    printed once to the server log (reference seeds an admin similarly)."""
    if state.list_users():
        return
    password = secrets.token_urlsafe(12)
    create_user('admin', password, role=rbac.ADMIN_ROLE)
    from skypilot_tpu import sky_logging
    sky_logging.init_logger(__name__).warning(
        f'Bootstrapped admin user with password: {password} '
        '(change it with `xsky users create admin <newpass>`)')
