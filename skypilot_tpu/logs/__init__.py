"""Cluster log shipping agents (twin of sky/logs/).

An agent renders the setup command that installs a log shipper on every
cluster host; selection via config key `logs.store` ('gcp' → Cloud
Logging, 'aws' → CloudWatch, both over fluent-bit like the reference).
"""
from __future__ import annotations

from typing import Any, Dict

from skypilot_tpu.logs.agent import LoggingAgent
from skypilot_tpu.logs.aws import AwsLoggingAgent
from skypilot_tpu.logs.gcp import GcpLoggingAgent

_AGENTS = {
    'gcp': GcpLoggingAgent,
    'aws': AwsLoggingAgent,
}


def get_logging_agent(store: str, config: Dict[str, Any]) -> LoggingAgent:
    if store not in _AGENTS:
        raise ValueError(f'Unknown log store {store!r}; known: '
                         f'{sorted(_AGENTS)}')
    return _AGENTS[store](config)
