"""IBM VPC provisioner tests against an in-memory API fake."""
from __future__ import annotations

from typing import Any, Dict, Optional

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.provision.ibm import instance as ibm_instance
from skypilot_tpu.provision.ibm import rest


class FakeIbm:
    """Minimal in-memory IBM VPC Gen2 API."""

    def __init__(self) -> None:
        self.region = 'us-south'
        self.vpcs: Dict[str, Dict[str, Any]] = {}
        self.subnets: Dict[str, Dict[str, Any]] = {}
        self.keys: Dict[str, Dict[str, Any]] = {}
        self.instances: Dict[str, Dict[str, Any]] = {}
        self.fips: Dict[str, Dict[str, Any]] = {}
        self.sg_rules: Dict[str, list] = {}
        self.fail_create: Optional[rest.IbmApiError] = None
        self._next = 0

    def _id(self, kind: str) -> str:
        self._next += 1
        return f'{kind}-{self._next:04d}'

    def paged(self, path: str, key: str, query=None):
        return self.call('GET', path, query=query).get(key, [])

    def call(self, method: str, path: str, body=None, query=None):
        if path == '/vpcs' and method == 'GET':
            return {'vpcs': list(self.vpcs.values())}
        if path == '/vpcs' and method == 'POST':
            vid = self._id('vpc')
            sg_id = self._id('sg')
            self.sg_rules[sg_id] = []
            vpc = dict(body, id=vid,
                       default_security_group={'id': sg_id})
            self.vpcs[vid] = vpc
            return vpc
        if path.startswith('/vpcs/'):
            return self.vpcs[path.split('/')[2]]
        if path == '/subnets' and method == 'GET':
            return {'subnets': list(self.subnets.values())}
        if path == '/subnets' and method == 'POST':
            sid = self._id('subnet')
            subnet = dict(body, id=sid)
            self.subnets[sid] = subnet
            return subnet
        if path == '/keys' and method == 'GET':
            return {'keys': list(self.keys.values())}
        if path == '/keys' and method == 'POST':
            kid = self._id('key')
            key = dict(body, id=kid)
            self.keys[kid] = key
            return key
        if path == '/images':
            return {'images': [
                {'id': 'img-ubuntu-2204',
                 'name': 'ibm-ubuntu-22-04-4',
                 'operating_system': {'name': 'ubuntu-22-04-amd64',
                                      'architecture': 'amd64'}}]}
        if path == '/instances' and method == 'GET':
            return {'instances': list(self.instances.values())}
        if path == '/instances' and method == 'POST':
            if self.fail_create is not None:
                err, self.fail_create = self.fail_create, None
                raise err
            iid = self._id('inst')
            n = self._next
            inst = dict(body, id=iid, status='running',
                        primary_network_interface={
                            'id': f'nic-{iid}',
                            'primary_ip': {'address': f'10.240.0.{n}'}})
            self.instances[iid] = inst
            return inst
        if path.endswith('/actions') and method == 'POST':
            iid = path.split('/')[2]
            if body['type'] == 'stop':
                self.instances[iid]['status'] = 'stopped'
            else:
                self.instances[iid]['status'] = 'running'
            return {}
        if path.startswith('/instances/') and method == 'DELETE':
            self.instances.pop(path.split('/')[2], None)
            return {}
        if path == '/floating_ips' and method == 'GET':
            return {'floating_ips': list(self.fips.values())}
        if path == '/floating_ips' and method == 'POST':
            fid = self._id('fip')
            fip = dict(body, id=fid, address=f'169.63.0.{self._next}')
            self.fips[fid] = fip
            return fip
        if path.startswith('/floating_ips/') and method == 'PATCH':
            self.fips[path.split('/')[2]].update(body)
            return {}
        if path.startswith('/floating_ips/') and method == 'DELETE':
            self.fips.pop(path.split('/')[2], None)
            return {}
        if path.endswith('/rules') and method == 'GET':
            return {'rules': list(self.sg_rules[path.split('/')[2]])}
        if path.endswith('/rules') and method == 'POST':
            self.sg_rules[path.split('/')[2]].append(body)
            return body
        raise AssertionError(f'unhandled IBM call {method} {path}')


@pytest.fixture()
def fake_ibm(monkeypatch, tmp_path):
    fake = FakeIbm()
    monkeypatch.setattr(ibm_instance, '_transport_factory',
                        lambda region: fake)
    from skypilot_tpu import authentication
    monkeypatch.setattr(authentication, 'PRIVATE_KEY_PATH',
                        str(tmp_path / 'key'))
    monkeypatch.setattr(authentication, 'PUBLIC_KEY_PATH',
                        str(tmp_path / 'key.pub'))
    yield fake


PROVIDER: Dict[str, Any] = {'region': 'us-south'}


def _config(count=1, itype='gx2-8x64x1v100'):
    return common.ProvisionConfig(
        provider_config=dict(PROVIDER),
        node_config={'instance_type': itype, 'disk_size': 100,
                     'ssh_public_key': 'ssh-ed25519 AAAA test'},
        count=count)


def test_launch_lifecycle(fake_ibm):
    record = ibm_instance.run_instances('us-south', 'us-south-1', 'c1',
                                        _config(count=2))
    assert len(record.created_instance_ids) == 2
    # VPC + zonal subnet + key registered exactly once.
    assert len(fake_ibm.vpcs) == 1
    assert len(fake_ibm.subnets) == 1
    assert len(fake_ibm.keys) == 1
    # Head (and only head) carries the floating IP.
    info = ibm_instance.get_cluster_info('us-south', 'c1', PROVIDER)
    hosts = info.sorted_instances()
    assert hosts[0].external_ip and hosts[1].external_ip is None
    assert all(h.internal_ip for h in hosts)
    ibm_instance.terminate_instances('c1', PROVIDER)
    assert ibm_instance.query_instances('c1', PROVIDER) == {}
    assert not fake_ibm.fips  # FIP released with the cluster


def test_idempotent_relaunch_reuses_network(fake_ibm):
    ibm_instance.run_instances('us-south', 'us-south-1', 'c2', _config())
    record = ibm_instance.run_instances('us-south', 'us-south-1', 'c2',
                                        _config())
    assert record.created_instance_ids == []
    assert len(fake_ibm.vpcs) == 1 and len(fake_ibm.subnets) == 1


def test_stop_resume(fake_ibm):
    ibm_instance.run_instances('us-south', 'us-south-1', 'c3', _config())
    ibm_instance.stop_instances('c3', PROVIDER)
    assert set(ibm_instance.query_instances('c3', PROVIDER).values()) == \
        {'STOPPED'}
    ibm_instance.run_instances('us-south', 'us-south-1', 'c3', _config())
    assert set(ibm_instance.query_instances('c3', PROVIDER).values()) == \
        {'RUNNING'}


def test_capacity_error_classified(fake_ibm):
    fake_ibm.fail_create = rest.IbmApiError(
        409, 'over_capacity',
        'Insufficient capacity in zone us-south-1.')
    with pytest.raises(exceptions.CapacityError):
        ibm_instance.run_instances('us-south', 'us-south-1', 'c4',
                                   _config())


def test_open_ports_on_default_sg(fake_ibm):
    ibm_instance.run_instances('us-south', 'us-south-1', 'c5', _config())
    ibm_instance.open_ports('c5', ['8080', '9000-9010'], PROVIDER)
    ibm_instance.open_ports('c5', ['8080'], PROVIDER)  # idempotent
    sg_id = next(iter(fake_ibm.sg_rules))
    rules = fake_ibm.sg_rules[sg_id]
    assert len(rules) == 2
    assert {(r['port_min'], r['port_max']) for r in rules} == \
        {(8080, 8080), (9000, 9010)}


def test_cloud_feasibility_and_pricing():
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu.utils import registry
    cloud = registry.CLOUD_REGISTRY.from_str('ibm')
    r = resources_lib.Resources(accelerators='V100:1')
    feasible, _ = cloud.get_feasible_launchable_resources(r)
    assert feasible
    assert feasible[0].instance_type == 'gx2-8x64x1v100'
    assert feasible[0].get_hourly_cost() == pytest.approx(2.54)
    # No spot market.
    regions = cloud.regions_with_offering('gx2-8x64x1v100', None,
                                          use_spot=True, region=None,
                                          zone=None)
    assert regions == []


def test_check_credentials(monkeypatch, tmp_path):
    from skypilot_tpu.utils import registry
    cloud = registry.CLOUD_REGISTRY.from_str('ibm')
    monkeypatch.delenv('IBM_API_KEY', raising=False)
    monkeypatch.setattr(rest, 'CREDENTIALS_PATH',
                        str(tmp_path / 'credentials.yaml'))
    ok, reason = cloud.check_credentials()
    assert not ok and 'IBM_API_KEY' in reason
    (tmp_path / 'credentials.yaml').write_text(
        'iam_api_key: abc123\nresource_group_id: rg1\n')
    ok, _ = cloud.check_credentials()
    assert ok
