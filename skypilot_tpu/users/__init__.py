"""User accounts + RBAC (twin of sky/users/)."""
