"""Nebius AI Cloud REST transport.

Role twin of the nebius SDK use in sky/provision/nebius/ (the
reference drives the official gRPC SDK; this repo's dependency-free
stance uses Nebius's REST gateway instead — same resources: Compute
instances + disks under a project/parent id). Auth: a static IAM token
from $NEBIUS_IAM_TOKEN or ~/.nebius/credentials (the token file the
nebius CLI writes); project id from $NEBIUS_PROJECT_ID or
~/.nebius/NEBIUS_PROJECT_ID.txt.
"""
from __future__ import annotations

import json
import os
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, Optional, Tuple

from skypilot_tpu import exceptions

API_ENDPOINT = 'https://api.{region}.nebius.cloud'
TOKEN_PATH = '~/.nebius/credentials'
PROJECT_PATH = '~/.nebius/NEBIUS_PROJECT_ID.txt'
_MAX_ATTEMPTS = 4
_BACKOFF_S = 2.0


class NebiusApiError(Exception):

    def __init__(self, status: int, code: str, message: str) -> None:
        super().__init__(f'{code or status}: {message}')
        self.status = status
        self.code = code or str(status)
        self.message = message


def _read_first_line(path: str) -> Optional[str]:
    path = os.path.expanduser(path)
    if not os.path.exists(path):
        return None
    try:
        with open(path, encoding='utf-8') as f:
            return f.readline().strip() or None
    except OSError:
        return None


def load_credentials() -> Optional[Tuple[str, str]]:
    """(iam_token, project_id) from env or the nebius CLI files."""
    token = os.environ.get('NEBIUS_IAM_TOKEN') or \
        _read_first_line(TOKEN_PATH)
    project = os.environ.get('NEBIUS_PROJECT_ID') or \
        _read_first_line(PROJECT_PATH)
    if token and project:
        return token, project
    return None


def classify_error(e: NebiusApiError,
                   region: Optional[str] = None) -> Exception:
    text = f'{e.code} {e.message}'.lower()
    where = f' in {region}' if region else ''
    if 'resource_exhausted' in text or 'not enough capacity' in text or \
            'no capacity' in text:
        return exceptions.CapacityError(f'Nebius capacity{where}: {e}')
    if 'quota' in text:
        return exceptions.QuotaExceededError(f'Nebius quota{where}: {e}')
    if e.status in (401, 403):
        return exceptions.PermissionError_(f'Nebius auth: {e}')
    if e.status == 400 or 'invalid_argument' in text:
        return exceptions.InvalidRequestError(f'Nebius request: {e}')
    return exceptions.ProvisionError(f'Nebius API{where}: {e}')


class Transport:

    def __init__(self, region: str = 'eu-north1',
                 token: Optional[str] = None,
                 project: Optional[str] = None) -> None:
        if token is None or project is None:
            creds = load_credentials()
            if creds is None:
                raise exceptions.PermissionError_(
                    'Nebius credentials not found (set '
                    '$NEBIUS_IAM_TOKEN + $NEBIUS_PROJECT_ID or run '
                    '`nebius init`).')
            token, project = creds
        self._token = token
        self.project = project
        self.region = region

    def call(self, method: str, path: str,
             body: Optional[Dict[str, Any]] = None,
             query: Optional[Dict[str, Any]] = None) -> Any:
        url = API_ENDPOINT.format(region=self.region) + path
        if query:
            url += '?' + urllib.parse.urlencode(
                {k: v for k, v in query.items() if v is not None})
        data = json.dumps(body).encode() if body is not None else None
        for attempt in range(_MAX_ATTEMPTS):
            req = urllib.request.Request(
                url, data=data, method=method,
                headers={'Authorization': f'Bearer {self._token}',
                         'Content-Type': 'application/json'})
            try:
                with urllib.request.urlopen(req, timeout=60) as resp:
                    payload = resp.read()
                    return json.loads(payload) if payload else {}
            except urllib.error.HTTPError as e:
                if e.code in (429, 503) and attempt < _MAX_ATTEMPTS - 1:
                    time.sleep(_BACKOFF_S * (attempt + 1))
                    continue
                try:
                    err = json.loads(e.read() or b'{}')
                    raise NebiusApiError(e.code, err.get('code', ''),
                                         str(err.get('message', str(e))))
                except (ValueError, AttributeError):
                    raise NebiusApiError(e.code, '', str(e)) from e
            except urllib.error.URLError as e:
                raise exceptions.ProvisionError(
                    f'Nebius API unreachable: {e}') from e
        # Unreachable: every iteration returns or raises.
