"""Control-plane scale tests (the high-QPS state-layer PR):

* concurrent readers vs. the single writer — reads go to per-thread
  WAL connections and must neither block on the write lock nor ever
  see ``database is locked``;
* pagination correctness (limit/offset round-trip, stable ordering)
  on every converted listing surface;
* the status-only request poll fast path (no body/result
  deserialization while a request is in flight);
* the new indexes exist and actually serve the hot queries;
* journal write coalescing (batched appends, read-your-writes);
* the tier-1 ``bench_controlplane --smoke`` latency gate.
"""
import json
import os
import pickle
import sqlite3
import subprocess
import sys
import threading
import time

import pytest

REPO_ROOT = os.path.join(os.path.dirname(__file__), '..', '..')


@pytest.fixture
def tmp_state(monkeypatch, tmp_path):
    """Isolated state DB (fresh read/write connections)."""
    from skypilot_tpu import state
    monkeypatch.setenv('XSKY_STATE_DB', str(tmp_path / 'state.db'))
    monkeypatch.delenv('XSKY_JOURNAL_FLUSH_S', raising=False)
    state.reset_for_test()
    yield state
    state.reset_for_test()


@pytest.fixture
def req_db(monkeypatch, tmp_path):
    """Isolated requests DB."""
    from skypilot_tpu.server import requests_db
    monkeypatch.setenv('XSKY_SERVER_DB', str(tmp_path / 'requests.db'))
    requests_db.reset_for_test()
    yield requests_db
    requests_db.reset_for_test()


class TestConcurrentReaders:

    def test_reads_proceed_while_writer_lock_is_held(self, tmp_state):
        """The acceptance assertion for the read pool: a reader thread
        completes its query while another thread HOLDS the global
        write lock (pre-refactor, every read serialized on it)."""
        tmp_state.add_or_update_cluster('c0', {'h': 0}, ready=True)
        ready, gate, done = (threading.Event(), threading.Event(),
                            threading.Event())

        def reader():
            tmp_state.get_clusters()   # one-time read-conn init
            ready.set()
            gate.wait(timeout=10)
            assert tmp_state.get_clusters()[0]['name'] == 'c0'
            assert tmp_state.get_cluster_from_name('c0') is not None
            done.set()

        t = threading.Thread(target=reader, daemon=True)
        t.start()
        assert ready.wait(10)
        with tmp_state._lock:  # pylint: disable=protected-access
            gate.set()
            # The read must finish while we sit on the write lock.
            assert done.wait(5), 'reader blocked on the write lock'
        t.join(timeout=5)

    def test_sustained_readers_during_writes_no_locked_errors(
            self, tmp_state):
        """N reader threads hammer listings while a writer commits in
        a loop: no `database is locked`, no torn records."""
        for i in range(20):
            tmp_state.add_or_update_cluster(f'c{i}', {'h': i},
                                            ready=True)
        errors = []
        stop = threading.Event()

        def writer():
            i = 0
            while not stop.is_set():
                try:
                    tmp_state.add_or_update_cluster(
                        f'w{i % 50}', {'h': i}, ready=True)
                    tmp_state.record_recovery_event('scale.test',
                                                    f'cluster/w{i % 50}')
                except Exception as e:  # pylint: disable=broad-except
                    errors.append(('writer', repr(e)))
                i += 1

        def reader():
            while not stop.is_set():
                try:
                    records = tmp_state.get_clusters(limit=10)
                    assert len(records) <= 10
                    tmp_state.get_cluster_from_name('c3')
                    tmp_state.get_recovery_events(limit=5)
                    tmp_state.get_cluster_names()
                except Exception as e:  # pylint: disable=broad-except
                    errors.append(('reader', repr(e)))

        threads = [threading.Thread(target=writer, daemon=True)]
        threads += [threading.Thread(target=reader, daemon=True)
                    for _ in range(6)]
        for t in threads:
            t.start()
        time.sleep(1.5)
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert not errors, errors[:5]

    def test_read_pool_off_still_correct(self, tmp_state, monkeypatch):
        """XSKY_STATE_READ_POOL=0 (the bench's legacy mode) keeps the
        exact same results — it is a concurrency switch, not a
        semantic one."""
        for i in range(5):
            tmp_state.add_or_update_cluster(f'c{i}', {'h': i},
                                            ready=True)
        pooled = [r['name'] for r in tmp_state.get_clusters()]
        monkeypatch.setenv('XSKY_STATE_READ_POOL', '0')
        legacy = [r['name'] for r in tmp_state.get_clusters()]
        assert pooled == legacy

    def test_read_conns_follow_db_repoint(self, tmp_state, monkeypatch,
                                          tmp_path):
        """A cached per-thread read connection must not keep serving a
        previous test's DB after XSKY_STATE_DB moves."""
        tmp_state.add_or_update_cluster('old-db', {}, ready=True)
        assert tmp_state.get_cluster_from_name('old-db') is not None
        monkeypatch.setenv('XSKY_STATE_DB', str(tmp_path / 'other.db'))
        tmp_state.reset_for_test()
        assert tmp_state.get_cluster_from_name('old-db') is None


class TestPagination:

    def _seed(self, state, n=7):
        for i in range(n):
            state.add_or_update_cluster(f'c{i}', {'h': i}, ready=True)

    def test_cluster_pages_round_trip(self, tmp_state):
        self._seed(tmp_state)
        full = [r['name'] for r in tmp_state.get_clusters()]
        assert len(full) == 7
        pages = []
        for offset in range(0, 7, 3):
            pages += [r['name'] for r in tmp_state.get_clusters(
                limit=3, offset=offset)]
        assert pages == full          # no overlap, no gaps, same order
        assert tmp_state.get_clusters(limit=0) == []
        assert [r['name'] for r in tmp_state.get_clusters(
            limit=100, offset=5)] == full[5:]

    def test_cluster_names_projection_filters_and_limits(self,
                                                         tmp_state):
        """The names-only projection (the /metrics live filter and the
        reconciler's orphan scans): status filter served by the
        clusters(status) index, limit clamps the page."""
        self._seed(tmp_state, n=4)
        tmp_state.update_cluster_status('c1',
                                        tmp_state.ClusterStatus.STOPPED)
        assert tmp_state.get_cluster_names() == ['c0', 'c1', 'c2', 'c3']
        assert tmp_state.get_cluster_names(
            status=tmp_state.ClusterStatus.UP) == ['c0', 'c2', 'c3']
        assert tmp_state.get_cluster_names(
            status=tmp_state.ClusterStatus.STOPPED) == ['c1']
        assert tmp_state.get_cluster_names(limit=2) == ['c0', 'c1']

    def test_cluster_name_filter_pushdown(self, tmp_state):
        self._seed(tmp_state)
        full = [r['name'] for r in tmp_state.get_clusters()]
        picked = [r['name'] for r in tmp_state.get_clusters(
            names=['c5', 'c2'])]
        assert picked == [n for n in full if n in ('c2', 'c5')]
        assert tmp_state.get_clusters(names=[]) == []
        assert tmp_state.count_clusters() == 7

    def test_core_status_pagination_and_point_lookup(self, tmp_state):
        from skypilot_tpu import core
        self._seed(tmp_state)
        page = core.status(limit=2, offset=2)
        assert len(page) == 2
        full = core.status()
        assert [r['name'] for r in page] == \
            [r['name'] for r in full[2:4]]
        point = core.status(cluster_names=['c4'])
        assert [r['name'] for r in point] == ['c4']

    def test_history_pages(self, tmp_state):
        self._seed(tmp_state, n=5)
        for i in range(5):
            tmp_state.remove_cluster(f'c{i}', terminate=True)
        full = [r['name'] for r in tmp_state.get_cluster_history()]
        assert len(full) == 5
        paged = [r['name'] for r in
                 tmp_state.get_cluster_history(limit=2)]
        paged += [r['name'] for r in
                  tmp_state.get_cluster_history(limit=2, offset=2)]
        paged += [r['name'] for r in
                  tmp_state.get_cluster_history(limit=2, offset=4)]
        assert paged == full

    def test_journal_offset_pages(self, tmp_state):
        for i in range(6):
            tmp_state.record_recovery_event('page.test', f'x/{i}')
        newest_first = list(reversed(
            [r['scope'] for r in tmp_state.get_recovery_events(
                limit=100)]))
        window = [r['scope'] for r in tmp_state.get_recovery_events(
            limit=2, offset=2)]
        # offset skips the 2 newest; the window is returned
        # oldest-first like every journal read.
        assert window == list(reversed(newest_first[2:4]))

    def test_request_listing_offset(self, req_db):
        ids = [req_db.create(f'verb{i}', 'u', {}) for i in range(5)]
        del ids
        full = [r['request_id'] for r in req_db.list_requests(limit=50)]
        assert len(full) == 5
        paged = [r['request_id']
                 for r in req_db.list_requests(limit=2, offset=0)]
        paged += [r['request_id']
                  for r in req_db.list_requests(limit=2, offset=2)]
        paged += [r['request_id']
                  for r in req_db.list_requests(limit=2, offset=4)]
        assert paged == full

    def test_spans_and_telemetry_offset(self, tmp_state):
        tmp_state.record_spans([
            {'trace_id': 't1', 'span_id': f's{i}', 'name': f'op{i}',
             'start_ts': float(i), 'end_ts': float(i) + 1}
            for i in range(6)])
        full = [s['span_id'] for s in tmp_state.get_spans('t1')]
        assert [s['span_id']
                for s in tmp_state.get_spans('t1', limit=3, offset=3)] \
            == full[3:]
        tmp_state.record_workload_telemetry(
            'c1', 1, [{'rank': r, 'phase': 'step'} for r in range(6)])
        rows = tmp_state.get_workload_telemetry(cluster='c1')
        assert len(rows) == 6
        tail = tmp_state.get_workload_telemetry(cluster='c1', limit=2,
                                                offset=4)
        assert [r['rank'] for r in tail] == \
            [r['rank'] for r in rows[4:]]

    def test_jobs_and_serve_listings_page(self, monkeypatch, tmp_path):
        from skypilot_tpu.jobs import state as jobs_state
        from skypilot_tpu.serve import state as serve_state
        monkeypatch.setenv('XSKY_JOBS_DB', str(tmp_path / 'jobs.db'))
        monkeypatch.setenv('XSKY_SERVE_DB', str(tmp_path / 'serve.db'))
        for i in range(5):
            jobs_state.add_job(f'j{i}', {'name': f'j{i}'})
            serve_state.add_service(f'svc{i}', {}, 0)
        all_jobs = [j['job_id'] for j in jobs_state.get_jobs()]
        paged = [j['job_id']
                 for j in jobs_state.get_jobs(limit=2, offset=1)]
        assert paged == all_jobs[1:3]
        names = [s['name'] for s in serve_state.get_services()]
        assert names == sorted(names)
        assert [s['name'] for s in serve_state.get_services(
            limit=2, offset=2)] == names[2:4]
        assert [s['name'] for s in serve_state.get_services(
            names=['svc3'])] == ['svc3']


class TestPollFastPath:

    def test_get_status_matches_get(self, req_db):
        rid = req_db.create('status', 'alice', {'x': 1})
        fast, full = req_db.get_status(rid), req_db.get(rid)
        assert fast['status'] == full['status']
        assert fast['name'] == full['name']
        assert fast['user'] == full['user']
        assert fast['trace_id'] == full['trace_id']
        assert 'body' not in fast and 'result' not in fast
        assert req_db.get_status('nope') is None

    def test_inflight_poll_skips_deserialization(self, req_db):
        """While a request is RUNNING, neither the poll route nor the
        watchdog path may unpickle/parse the persisted payloads —
        proven by poisoning them with garbage bytes."""
        from skypilot_tpu.server import app as server_app
        rid = req_db.create('launch', 'u', {'big': 'body'})
        req_db.set_status(rid, req_db.RequestStatus.RUNNING)
        conn = req_db._get_conn()  # pylint: disable=protected-access
        conn.execute(
            'UPDATE requests SET body=?, result=? WHERE request_id=?',
            ('{not json', b'\x80not-a-pickle', rid))
        conn.commit()
        code, payload = server_app._get_request(  # pylint: disable=protected-access
            {'request_id': rid})
        assert code == 200
        assert payload['status'] == 'RUNNING'
        assert 'result' not in payload
        # get() on the poisoned row WOULD choke — the point of the
        # fast path is that the poll loop never goes there.
        with pytest.raises(Exception):
            req_db.get(rid)

    def test_terminal_poll_still_returns_result(self, req_db):
        from skypilot_tpu.server import app as server_app
        rid = req_db.create('status', 'u', {})
        req_db.finish(rid, result={'answer': 42})
        code, payload = server_app._get_request(  # pylint: disable=protected-access
            {'request_id': rid})
        assert code == 200
        assert payload['status'] == 'SUCCEEDED'
        assert payload['result'] == {'answer': 42}


class TestIndexes:

    def test_state_indexes_exist(self, tmp_state):
        tmp_state.add_or_update_cluster('c0', {}, ready=True)
        conn = sqlite3.connect(os.environ['XSKY_STATE_DB'])
        names = {r[0] for r in conn.execute(
            "SELECT name FROM sqlite_master WHERE type='index'")}
        assert 'idx_clusters_status' in names
        assert 'idx_clusters_workspace' in names
        assert 'idx_recovery_events_ts' in names
        assert 'idx_cluster_history_torn_down' in names
        plan = ' '.join(r[3] for r in conn.execute(
            "EXPLAIN QUERY PLAN SELECT name FROM clusters "
            "WHERE status='UP'"))
        assert 'idx_clusters_status' in plan

    def test_requests_indexes_serve_inflight_scan(self, req_db):
        req_db.create('x', 'u', {})
        conn = req_db._get_conn()  # pylint: disable=protected-access
        names = {r[0] for r in conn.execute(
            "SELECT name FROM sqlite_master WHERE type='index'")}
        assert 'idx_requests_status_finished' in names
        assert 'idx_requests_created' in names
        plan = ' '.join(r[3] for r in conn.execute(
            "EXPLAIN QUERY PLAN SELECT request_id FROM requests "
            "WHERE status IN ('PENDING', 'RUNNING')"))
        assert 'idx_requests_status_finished' in plan


class TestJournalCoalescing:

    def test_appends_coalesce_and_flush_on_read(self, tmp_state,
                                                monkeypatch):
        monkeypatch.setenv('XSKY_JOURNAL_FLUSH_S', '30')
        assert tmp_state.get_recovery_events() == []   # init the DB
        tmp_state.record_recovery_event('co.test', 'a/1')
        tmp_state.record_recovery_event('co.test', 'a/2')
        raw = sqlite3.connect(os.environ['XSKY_STATE_DB'])
        assert raw.execute(
            'SELECT COUNT(*) FROM recovery_events').fetchone()[0] == 0
        # Read-your-writes: the listing flushes the buffer first.
        assert len(tmp_state.get_recovery_events(scope='a')) == 2
        assert raw.execute(
            'SELECT COUNT(*) FROM recovery_events').fetchone()[0] == 2

    def test_buffer_cap_forces_flush(self, tmp_state, monkeypatch):
        monkeypatch.setenv('XSKY_JOURNAL_FLUSH_S', '3600')
        for i in range(tmp_state._JOURNAL_BUF_MAX):  # pylint: disable=protected-access
            tmp_state.record_recovery_event('cap.test', f'b/{i}')
        raw = sqlite3.connect(os.environ['XSKY_STATE_DB'])
        assert raw.execute(
            'SELECT COUNT(*) FROM recovery_events').fetchone()[0] == \
            tmp_state._JOURNAL_BUF_MAX  # pylint: disable=protected-access

    def test_default_is_immediate(self, tmp_state):
        tmp_state.record_recovery_event('imm.test', 'c/1')
        raw = sqlite3.connect(os.environ['XSKY_STATE_DB'])
        assert raw.execute(
            'SELECT COUNT(*) FROM recovery_events').fetchone()[0] == 1


class TestBenchSmoke:
    """Tier-1 latency gate: the bench's --smoke mode (hundreds of
    clusters, seconds of load) must pass its p99 gates — the CI tripwire
    for anyone re-serializing reads or fattening the poll path."""

    def test_bench_controlplane_smoke_gate(self, tmp_path):
        env = dict(os.environ)
        env.pop('XSKY_STATE_DB', None)
        env.pop('XSKY_SERVER_DB', None)
        env['JAX_PLATFORMS'] = 'cpu'
        out_path = tmp_path / 'bench.json'
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO_ROOT, 'tools', 'bench_controlplane.py'),
             '--smoke', '--json-out', str(out_path)],
            capture_output=True, text=True, timeout=240, env=env,
            check=False)
        assert proc.returncode == 0, \
            f'stdout: {proc.stdout}\nstderr: {proc.stderr[-2000:]}'
        record = json.loads(out_path.read_text())
        assert record['pass'] is True
        assert record['seeded']['clusters'] >= 100
        verbs = record['open_loop']['verbs']
        assert verbs['status']['completed'] > 0
        assert verbs['poll']['completed'] > 0
        assert sum(v['errors'] for v in verbs.values()) == 0
        # The p99 gates were actually evaluated (the before/after
        # speedup artifact and its ≥5x gate are full-mode, 5k-fleet
        # statements — docs/performance.md quotes that run).
        assert verbs['status']['p99_ms'] < record['gates'][
            'status_p99_ms']
        assert verbs['poll']['p99_ms'] < record['gates']['poll_p99_ms']

    def test_bench_multi_server_smoke_drill(self, tmp_path):
        """The --multi-server smoke rung: three servers on one shared
        DB survive a SIGKILL of the recorder-holding server — zero
        acked requests lost or double-executed, every orphaned role
        re-owned within one lease TTL with trace-linked journal rows,
        no double-folded rollup buckets, goodput floors monotone. The
        ≥2x status-QPS scaling number is reported but gated only by
        the full run (a 2-core CI box cannot scale three servers)."""
        env = dict(os.environ)
        env.pop('XSKY_STATE_DB', None)
        env.pop('XSKY_SERVER_DB', None)
        env['JAX_PLATFORMS'] = 'cpu'
        out_path = tmp_path / 'bench-multi.json'
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO_ROOT, 'tools', 'bench_controlplane.py'),
             '--multi-server', '--smoke', '--json-out', str(out_path)],
            capture_output=True, text=True, timeout=360, env=env,
            check=False)
        assert proc.returncode == 0, \
            f'stdout: {proc.stdout}\nstderr: {proc.stderr[-2000:]}'
        record = json.loads(out_path.read_text())
        assert record['pass'] is True
        multi = record['multi_server']
        assert multi['failures'] == []
        assert multi['servers'] >= 3
        # The drill actually happened: a victim was killed with work
        # acked, its recorder role was re-owned inside one TTL, and
        # the request-id audit found nothing lost.
        assert multi['acked_requests'] > 0
        assert multi['requests_lost'] == 0
        assert multi['recorder_reown_s'] is not None
        assert multi['recorder_reown_s'] <= multi['lease_ttl_s']
        assert multi['repairs']['role_takeovers'] >= 1
        assert (multi['repairs']['requests_requeued'] +
                multi['repairs']['requests_aborted']) > 0
        assert multi['rollup']['rows_1m'] > 0
        assert multi['rollup']['duplicate_buckets'] == 0
