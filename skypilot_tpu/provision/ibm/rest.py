"""IBM Cloud VPC (Gen2) REST transport: IAM token exchange, no SDK.

Role twin of the reference's ibm adaptor (sky/adaptors/ibm.py, which
wraps ibm_vpc.VpcV1 + IAMAuthenticator), redesigned for this repo's
transport pattern: the API key from ~/.ibm/credentials.yaml (the same
file the reference reads) is exchanged at iam.cloud.ibm.com for a
bearer token (cached until ~5 min before expiry), and `call()` hits
the regional VPC endpoint with the mandatory `version` + `generation=2`
query params. Errors map onto the failover engine's typed taxonomy.
"""
from __future__ import annotations

import json
import os
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, Optional

from skypilot_tpu import exceptions

CREDENTIALS_PATH = '~/.ibm/credentials.yaml'
IAM_ENDPOINT = 'https://iam.cloud.ibm.com/identity/token'
_API_VERSION = '2024-04-30'
_MAX_ATTEMPTS = 4
_BACKOFF_S = 2.0


class IbmApiError(Exception):

    def __init__(self, status: int, code: str, message: str) -> None:
        super().__init__(f'{code or status}: {message}')
        self.status = status
        self.code = code or str(status)
        self.message = message


def load_credentials() -> Optional[Dict[str, str]]:
    """$IBM_API_KEY, else the reference-compatible yaml-ish key file
    (`iam_api_key: ...` lines in ~/.ibm/credentials.yaml)."""
    out: Dict[str, str] = {}
    key = os.environ.get('IBM_API_KEY')
    if key:
        out['iam_api_key'] = key
    path = os.path.expanduser(CREDENTIALS_PATH)
    if os.path.exists(path):
        try:
            with open(path, encoding='utf-8') as f:
                for line in f:
                    if ':' in line and not line.lstrip().startswith('#'):
                        field, _, value = line.partition(':')
                        out.setdefault(field.strip(),
                                       value.strip().strip('\'"'))
        except OSError:
            pass
    if 'iam_api_key' not in out:
        return None
    return out


def classify_error(e: IbmApiError,
                   region: Optional[str] = None) -> Exception:
    text = f'{e.code} {e.message}'.lower()
    where = f' in {region}' if region else ''
    if ('insufficient' in text and 'capacity' in text) or \
            'out of stock' in text or e.code == 'over_capacity':
        return exceptions.CapacityError(f'IBM capacity{where}: {e}')
    if 'quota' in text or e.code == 'quota_exceeded':
        return exceptions.QuotaExceededError(f'IBM quota{where}: {e}')
    if e.status in (401, 403):
        return exceptions.PermissionError_(f'IBM auth: {e}')
    if e.status == 400:
        return exceptions.InvalidRequestError(f'IBM request: {e}')
    return exceptions.ProvisionError(f'IBM API{where}: {e}')


class Transport:
    """Authenticated VPC calls for one region."""

    def __init__(self, region: str,
                 api_key: Optional[str] = None) -> None:
        if api_key is None:
            creds = load_credentials()
            if creds is None:
                raise exceptions.PermissionError_(
                    'IBM API key not found (set $IBM_API_KEY or '
                    f'populate {CREDENTIALS_PATH}).')
            api_key = creds['iam_api_key']
        self._api_key = api_key
        self.region = region
        self._token: Optional[str] = None
        self._token_expiry = 0.0

    def _bearer(self) -> str:
        if self._token is None or time.time() > self._token_expiry - 300:
            body = urllib.parse.urlencode({
                'grant_type': 'urn:ibm:params:oauth:grant-type:apikey',
                'apikey': self._api_key}).encode()
            req = urllib.request.Request(
                IAM_ENDPOINT, data=body, method='POST',
                headers={'Content-Type':
                         'application/x-www-form-urlencoded',
                         'Accept': 'application/json'})
            try:
                with urllib.request.urlopen(req, timeout=60) as resp:
                    payload = json.loads(resp.read())
            except urllib.error.HTTPError as e:
                raise exceptions.PermissionError_(
                    f'IBM IAM token exchange failed: {e}') from e
            except urllib.error.URLError as e:
                raise exceptions.ProvisionError(
                    f'IBM IAM unreachable: {e}') from e
            self._token = payload['access_token']
            self._token_expiry = time.time() + payload.get('expires_in',
                                                           3600)
        return self._token

    def call(self, method: str, path: str,
             body: Optional[Dict[str, Any]] = None,
             query: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        params = {'version': _API_VERSION, 'generation': '2'}
        params.update({k: v for k, v in (query or {}).items()
                       if v is not None})
        url = (f'https://{self.region}.iaas.cloud.ibm.com/v1{path}'
               f'?{urllib.parse.urlencode(params)}')
        data = json.dumps(body).encode() if body is not None else None
        for attempt in range(_MAX_ATTEMPTS):
            req = urllib.request.Request(
                url, data=data, method=method,
                headers={'Authorization': f'Bearer {self._bearer()}',
                         'Content-Type': 'application/json',
                         'Accept': 'application/json'})
            try:
                with urllib.request.urlopen(req, timeout=60) as resp:
                    payload = resp.read()
                    return json.loads(payload) if payload else {}
            except urllib.error.HTTPError as e:
                if e.code in (429, 503) and attempt < _MAX_ATTEMPTS - 1:
                    time.sleep(_BACKOFF_S * (attempt + 1))
                    continue
                try:
                    err = json.loads(e.read() or b'{}')
                    first = (err.get('errors') or [{}])[0]
                    raise IbmApiError(e.code, first.get('code', ''),
                                      first.get('message', str(e)))
                except (ValueError, AttributeError, IndexError):
                    raise IbmApiError(e.code, '', str(e)) from e
            except urllib.error.URLError as e:
                raise exceptions.ProvisionError(
                    f'IBM API unreachable: {e}') from e
        # Unreachable: every iteration returns or raises.

    def paged(self, path: str, key: str,
              query: Optional[Dict[str, Any]] = None) -> list:
        """GET all pages (VPC `start` cursor via the `next` href) — a
        busy account must never hide cluster nodes past page one
        (duplicate-launch / missed-terminate hazard)."""
        out: list = []
        start: Optional[str] = None
        while True:
            q = dict(query or {}, limit=100)
            if start:
                q['start'] = start
            reply = self.call('GET', path, query=q)
            out.extend(reply.get(key, []))
            href = (reply.get('next') or {}).get('href')
            if not href:
                return out
            start = urllib.parse.parse_qs(
                urllib.parse.urlparse(href).query).get('start',
                                                       [None])[0]
            if not start:
                return out
