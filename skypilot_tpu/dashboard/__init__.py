"""Web dashboard (minimal static twin of sky/dashboard's Next.js app)."""
import os

STATIC_DIR = os.path.dirname(__file__)


def index_html() -> bytes:
    with open(os.path.join(STATIC_DIR, 'index.html'), 'rb') as f:
        return f.read()
