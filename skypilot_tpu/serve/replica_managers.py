"""Replica manager (twin of sky/serve/replica_managers.py:60,388).

Launches/terminates replica clusters through the ordinary launch stack
(recursive execution.launch, like the reference), probes readiness over
HTTP, and detects preempted replicas via cloud-truth status refresh.
"""
from __future__ import annotations

import concurrent.futures
import os
import socket
import threading
import time
import urllib.request
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu import state as global_state
from skypilot_tpu import task as task_lib
from skypilot_tpu.serve import service_spec as spec_lib
from skypilot_tpu.serve import state as serve_state
from skypilot_tpu.utils import chaos
from skypilot_tpu.utils import common_utils
from skypilot_tpu.utils import resilience

logger = sky_logging.init_logger(__name__)

# Readiness probe RETRIES per replica per tick, on top of the initial
# probe (a single dropped HTTP request must not flap READY →
# NOT_READY). "0 retries" still means one probe.
_PROBE_ATTEMPTS = 1 + max(
    0, int(os.environ.get('XSKY_SERVE_PROBE_RETRIES', '1')))
_PROBE_TIMEOUT_S = float(os.environ.get('XSKY_SERVE_PROBE_TIMEOUT', '5'))

# Graceful drain: a draining replica stops admitting new requests (the
# LB answers 503+Retry-After) and keeps serving inflight ones until
# they finish or this deadline passes, then terminates.
_DRAIN_DEADLINE_S = float(os.environ.get('XSKY_DRAIN_DEADLINE_S', '30'))
# When a spot replica's preemption is journalled, one READY spot peer
# sharing its placement (same zone about to be reclaimed) is drained
# pre-emptively instead of waiting for the hard kill. 0 disables.
_DRAIN_ON_PREEMPTION = os.environ.get(
    'XSKY_DRAIN_ON_PREEMPTION', '1') != '0'


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        return s.getsockname()[1]


class ReplicaManager:

    def __init__(self, service_name: str, task_config: Dict[str, Any],
                 spec: spec_lib.SkyServiceSpec,
                 version: int = 1) -> None:
        self.service_name = service_name
        self.task_config = dict(task_config)
        self.task_config.pop('service', None)
        self.spec = spec
        # Rolling-update state: replicas are stamped with the version
        # they were launched at (twin of ReplicaInfo.version,
        # sky/serve/replica_managers.py:388); scale decisions apply to
        # the current version, old versions drain after the new fleet
        # is ready.
        self.version = version
        existing = serve_state.get_replicas(service_name)
        self._next_replica_id = 1 + max(
            [r['replica_id'] for r in existing], default=0)
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=4, thread_name_prefix=f'replica-{service_name}')
        self._launching: Dict[int, concurrent.futures.Future] = {}
        self._lock = threading.Lock()
        # Consecutive launch failures (service declared FAILED past this).
        self.launch_failures = 0
        self.max_launch_failures = 3
        # Spot zone tracking (twin of sky/serve/spot_placer.py:254):
        # learns zones as replicas come up; preempted zones are avoided
        # and trigger on-demand fallback when the spec allows.
        from skypilot_tpu.serve import spot_placer as spot_placer_lib
        self.spot_placer = spot_placer_lib.DynamicFallbackSpotPlacer([])
        self._replica_zone: Dict[int, str] = {}
        # Structured (cloud, region, zone, sku) of each replica's
        # launched placement, captured at launch success — preemption
        # journal rows carry it so the shared fleet placement scorer
        # (jobs/fleet.py) counts serve preemptions too.
        self._replica_placement: Dict[int, Dict[str, Any]] = {}
        # Preemption-detection timestamps: journal recovery latency when
        # the replacement launches.
        self._preempted_at: Dict[int, float] = {}
        # Graceful drains in flight: replica_id → {'since', 'deadline',
        # 'reason', 'detector', 'ident', 'trace_id'}. Drain flags
        # survive a controller restart via the replicas.draining
        # column; the in-memory meta re-anchors the deadline at adopt
        # time (a restarted controller re-grants the full deadline —
        # cheaper than persisting start timestamps for a rare path).
        self._draining: Dict[int, Dict[str, Any]] = {}
        for r in existing:
            if r.get('draining'):
                self._draining[r['replica_id']] = {
                    'since': time.time(),
                    'deadline': _DRAIN_DEADLINE_S,
                    'reason': 'adopted at controller restart',
                    'detector': None, 'ident': None, 'trace_id': None}

    # ---- scaling ----

    def replicas(self) -> List[Dict[str, Any]]:
        return serve_state.get_replicas(self.service_name)

    def apply_update(self, task_config: Dict[str, Any],
                     spec: spec_lib.SkyServiceSpec, version: int) -> None:
        """Adopt a new service version (rolling update entry point)."""
        self.task_config = dict(task_config)
        self.task_config.pop('service', None)
        self.spec = spec
        self.version = version
        self.launch_failures = 0

    def _is_active(self, r: Dict[str, Any]) -> bool:
        return not r['status'].is_terminal()

    def active_count(self, version: Optional[int] = None,
                     spot: Optional[bool] = None,
                     include_draining: bool = True) -> int:
        return len([
            r for r in self.replicas() if self._is_active(r) and
            (version is None or r['version'] == version) and
            (spot is None or r['spot'] == spot) and
            (include_draining or not r['draining'])
        ])

    def ready_spot_count(self) -> int:
        # Across ALL versions: during a rolling update the old fleet
        # keeps serving until reconcile_versions drains it, so its
        # READY spot replicas are real capacity — filtering them out
        # would spin up a spurious on-demand fleet on every update.
        return len([
            r for r in self.replicas()
            if r['spot'] and
            r['status'] == serve_state.ReplicaStatus.READY
        ])

    def scale_to(self, target: int,
                 target_ondemand: Optional[int] = None) -> None:
        """Launch/terminate current-version replicas toward target.

        With `target_ondemand` (mixed spot fleets), `target` counts the
        task's own (spot) replicas and `target_ondemand` replicas are
        forced on-demand — each kind scales independently.

        Old-version replicas are untouched here — they keep serving
        until reconcile_versions() drains them, so an update never drops
        below the pre-update capacity.
        """
        with self._lock:
            if target_ondemand is None:
                self._scale_kind(target, spot=None)
            else:
                self._scale_kind(target, spot=True)
                self._scale_kind(target_ondemand, spot=False)

    def _scale_kind(self, target: int, spot: Optional[bool]) -> None:
        # Draining replicas are already on the way out: they don't
        # count toward target (the replacement launches while the
        # drain finishes) and are never scale-down candidates.
        current = self.active_count(version=self.version, spot=spot,
                                    include_draining=False)
        for _ in range(max(0, target - current)):
            self._start_replica(spot=spot is not False)
        if current > target:
            # Terminate youngest non-ready first, then youngest ready.
            candidates = sorted(
                [r for r in self.replicas()
                 if r['version'] == self.version and r['status'] not in
                 (serve_state.ReplicaStatus.SHUTTING_DOWN,) and
                 not r['draining'] and
                 (spot is None or r['spot'] == spot)],
                key=lambda r: (
                    r['status'] == serve_state.ReplicaStatus.READY,
                    -r['replica_id']))
            for r in candidates[:current - target]:
                self.terminate_replica(r['replica_id'])

    def reconcile_versions(self, target: int) -> None:
        """Drain old-version replicas once the new fleet is ready.

        (Twin of the reference's rolling update: old replicas terminate
        only after >= target new-version replicas pass readiness.)
        """
        old = [r for r in self.replicas()
               if r['version'] < self.version and
               r['status'] != serve_state.ReplicaStatus.SHUTTING_DOWN]
        if not old:
            return
        ready_new = len([
            r for r in self.replicas()
            if r['version'] == self.version and
            r['status'] == serve_state.ReplicaStatus.READY
        ])
        if ready_new >= max(1, target):
            with self._lock:
                for r in old:
                    logger.info(
                        f'Rolling update: draining replica '
                        f'{r["replica_id"]} (v{r["version"]} -> '
                        f'v{self.version}).')
                    self.terminate_replica(r['replica_id'])

    def _start_replica(self, spot: bool = True) -> int:
        replica_id = self._next_replica_id
        self._next_replica_id += 1
        cluster_name = f'xsky-serve-{self.service_name}-{replica_id}'
        serve_state.upsert_replica(self.service_name, replica_id,
                                   cluster_name,
                                   serve_state.ReplicaStatus.PROVISIONING,
                                   version=self.version, spot=spot)
        future = self._pool.submit(self._launch_replica, replica_id,
                                   cluster_name, self.version, spot)
        self._launching[replica_id] = future
        return replica_id

    def _launch_replica(self, replica_id: int, cluster_name: str,
                        version: int, spot: bool = True) -> None:
        try:
            from skypilot_tpu import execution
            task = task_lib.Task.from_yaml_config(self.task_config)
            # An on-demand fallback replica of a spot fleet, or zone
            # fallback after repeated spot preemptions.
            force_ondemand = not spot
            if (spot and self.spec.use_ondemand_fallback and
                    task.resources[0].use_spot and
                    self.spot_placer.should_fallback_to_ondemand() and
                    self.spot_placer.preemptive_zones):
                logger.info(f'Replica {replica_id}: all spot zones '
                            'preempted recently; falling back to '
                            'on-demand.')
                force_ondemand = True
            if force_ondemand:
                task.set_resources(
                    [r.copy(use_spot=False) for r in task.resources])
            port = self.spec.replica_port or _free_port()
            # Local/fake replicas share one loopback: give each its own
            # port via $PORT (real clouds use the spec port on the
            # replica's IP, like GKE service port mapping).
            task.update_envs({'PORT': str(port)})
            # Feed the placer's preemption knowledge into the launch's
            # failover blocklist: provisioning SKIPS recently-preempted
            # zones instead of re-rolling the same dice (VERDICT r3
            # weak #6 — the placer was disconnected from the blocklist
            # the backend already honors). Three deliberate limits:
            # only for launches that actually USE spot (an on-demand
            # replica dying says nothing about preemption), scoped to
            # the spot provisioning model (a spot preemption must not
            # block the same zone's on-demand failover candidate), and
            # only while the placer still knows a good zone — with
            # every learned zone preemptive, blocking them all would
            # leave no recovery path (SpotPlacer._reset's "try
            # somewhere" rule, applied here).
            blocked = None
            launch_uses_spot = (not force_ondemand and
                                any(r.use_spot for r in task.resources))
            if launch_uses_spot and self.spot_placer.preemptive_zones \
                    and self.spot_placer.active_zones:
                from skypilot_tpu import resources as resources_lib
                blocked = [
                    resources_lib.Resources(
                        zone=z,
                        accelerator_args={'provisioning_model': 'spot'})
                    for z in sorted(self.spot_placer.preemptive_zones)]
            job_id, handle = execution.launch(
                task, cluster_name=cluster_name, detach_run=True,
                blocked_resources=blocked)
            local = handle.is_local_provider
            host = '127.0.0.1' if local else handle.head_ip
            zone = handle.launched_resources.zone
            if zone:
                self._replica_zone[replica_id] = zone
                self.spot_placer.handle_active(zone)
            from skypilot_tpu.jobs import fleet
            self._replica_placement[replica_id] = {
                k: v for k, v in fleet.placement_key(
                    handle.launched_resources).items() if v}
            self.launch_failures = 0
            if not any(r['replica_id'] == replica_id
                       for r in self.replicas()):
                # The row was removed mid-launch (scale-down terminated
                # a PROVISIONING replica): re-inserting it would
                # resurrect a replica the controller already drained —
                # tear the just-launched cluster down instead.
                logger.info(f'Replica {replica_id} was terminated '
                            'mid-launch; tearing down its cluster.')
                from skypilot_tpu import core as core_lib
                try:
                    core_lib.down(cluster_name, purge=True)
                except Exception:  # pylint: disable=broad-except
                    pass
                return
            serve_state.upsert_replica(
                self.service_name, replica_id, cluster_name,
                serve_state.ReplicaStatus.STARTING,
                endpoint=f'{host}:{port}', version=version, spot=spot,
                job_id=job_id)
        except Exception as e:  # pylint: disable=broad-except
            logger.warning(f'Replica {replica_id} launch failed: {e}')
            self.launch_failures += 1
            serve_state.upsert_replica(self.service_name, replica_id,
                                       cluster_name,
                                       serve_state.ReplicaStatus.FAILED,
                                       version=version, spot=spot)

    def terminate_replica(self, replica_id: int) -> None:
        record = next((r for r in self.replicas()
                       if r['replica_id'] == replica_id), None)
        if record is None:
            return
        serve_state.upsert_replica(self.service_name, replica_id,
                                   record['cluster_name'],
                                   serve_state.ReplicaStatus.SHUTTING_DOWN)
        from skypilot_tpu import core as core_lib
        try:
            core_lib.down(record['cluster_name'], purge=True)
        except exceptions.ClusterDoesNotExist:
            pass
        serve_state.remove_replica(self.service_name, replica_id)

    def terminate_all(self) -> None:
        for r in self.replicas():
            self.terminate_replica(r['replica_id'])

    # ---- graceful drain ----

    def drain_replica(self, replica_id: int, reason: str = '',
                      detector: Optional[str] = None,
                      ident: Optional[str] = None,
                      trace_id: Optional[str] = None,
                      deadline_s: Optional[float] = None) -> bool:
        """Start a graceful drain: stop admitting (the replica leaves
        serving_endpoints and the LB answers 503+Retry-After for it),
        finish inflight requests under the deadline, then terminate
        (tick_drains). Idempotent: returns False if the replica is
        already draining, terminal, or unknown."""
        record = next((r for r in self.replicas()
                       if r['replica_id'] == replica_id), None)
        if record is None or record['status'].is_terminal() or \
                record['draining'] or replica_id in self._draining:
            return False
        serve_state.set_replica_draining(self.service_name, replica_id,
                                         True)
        self._draining[replica_id] = {
            'since': time.time(),
            'deadline': (deadline_s if deadline_s is not None
                         else _DRAIN_DEADLINE_S),
            'reason': reason, 'detector': detector, 'ident': ident,
            'trace_id': trace_id}
        logger.info(f'Replica {replica_id} draining: {reason}')
        return True

    def draining_endpoints(self) -> List[str]:
        """Endpoints mid-drain (the LB's 503+Retry-After set)."""
        return [r['endpoint'] for r in self.replicas()
                if r['draining'] and r['endpoint']]

    def tick_drains(self, inflight_by_endpoint: Dict[str, int],
                    now: Optional[float] = None) -> None:
        """Finish drains whose inflight hit zero or whose deadline
        passed; journal `replica.drained` with the drain latency."""
        now = now if now is not None else time.time()
        by_id = {r['replica_id']: r for r in self.replicas()}
        for rid in list(self._draining):
            meta = self._draining[rid]
            record = by_id.get(rid)
            if record is None or record['status'].is_terminal():
                # Left by another path (preempted mid-drain, hard
                # scale-down): nothing left to terminate gracefully.
                del self._draining[rid]
                continue
            inflight = inflight_by_endpoint.get(
                record['endpoint'] or '', 0)
            expired = now - meta['since'] >= meta['deadline']
            if inflight > 0 and not expired:
                continue
            global_state.record_recovery_event(
                'replica.drained',
                scope=(f'service/{self.service_name}/replica/{rid}'),
                cause=meta['reason'] or 'drain',
                latency_s=now - meta['since'],
                detail={'expired': expired, 'inflight': inflight},
                trace_id=meta['trace_id'])
            del self._draining[rid]
            self.terminate_replica(rid)

    def _drain_preempted_peer(self, preempted_id: int,
                              placement: Dict[str, Any]) -> None:
        """Journalled preemption → pre-emptive peer drain: one READY
        spot peer sharing the reclaimed placement drains gracefully
        (and gets replaced) instead of waiting for its own hard kill.
        Capped at one peer per preemption and only while another
        non-draining READY replica remains, so a one-zone fleet can
        never drain itself dark."""
        if not _DRAIN_ON_PREEMPTION or not placement:
            return
        ready = [r for r in self.replicas()
                 if r['status'] == serve_state.ReplicaStatus.READY and
                 not r['draining']]
        peers = [r for r in ready
                 if r['spot'] and r['replica_id'] != preempted_id and
                 self._replica_placement.get(
                     r['replica_id']) == placement]
        if not peers or len(ready) - 1 < 1:
            return
        peer = peers[0]
        self.drain_replica(
            peer['replica_id'],
            reason=(f'placement shared with preempted replica '
                    f'{preempted_id}'),
            detector='preemption',
            ident=f'replica/{peer["replica_id"]}')

    # ---- probing ----

    def probe_all(self) -> int:
        """Probe readiness; mark preempted replicas; return ready count."""
        ready = 0
        for r in self.replicas():
            status = r['status']
            if status in (serve_state.ReplicaStatus.PROVISIONING,
                          serve_state.ReplicaStatus.SHUTTING_DOWN,
                          serve_state.ReplicaStatus.FAILED):
                continue
            if not self._cluster_alive(r['cluster_name']):
                zone = self._replica_zone.get(r['replica_id'])
                if zone:
                    self.spot_placer.handle_preemption(zone)
                self._preempted_at[r['replica_id']] = time.time()
                # Structured placement keys ride the row so the fleet
                # scorer counts this preemption against its zone/SKU.
                global_state.record_recovery_event(
                    'replica.preempted',
                    scope=(f'service/{self.service_name}/replica/'
                           f'{r["replica_id"]}'),
                    cause='cluster gone from cloud',
                    detail={'cluster': r['cluster_name'],
                            'zone': zone or '',
                            **self._replica_placement.get(
                                r['replica_id'], {})})
                serve_state.upsert_replica(
                    self.service_name, r['replica_id'],
                    r['cluster_name'],
                    serve_state.ReplicaStatus.PREEMPTED)
                # A journalled preemption opens a remediation (the
                # recovery is the action; recover_preempted resolves
                # it) and may pre-emptively drain one placement peer.
                from skypilot_tpu.utils import remediation
                remediation.record_applied(
                    scope=f'service/{self.service_name}',
                    detector='preemption',
                    ident=f'replica/{r["replica_id"]}',
                    action='recover_replica',
                    anomaly_scope=(f'service/{self.service_name}/'
                                   f'replica/{r["replica_id"]}'),
                    detail={'cluster': r['cluster_name'],
                            'zone': zone or ''})
                self._drain_preempted_peer(
                    r['replica_id'],
                    self._replica_placement.get(r['replica_id'], {}))
                continue
            if r['endpoint'] and self._probe(r['endpoint']):
                serve_state.upsert_replica(self.service_name,
                                           r['replica_id'],
                                           r['cluster_name'],
                                           serve_state.ReplicaStatus.READY)
                ready += 1
            elif status == serve_state.ReplicaStatus.READY:
                serve_state.upsert_replica(
                    self.service_name, r['replica_id'], r['cluster_name'],
                    serve_state.ReplicaStatus.NOT_READY)
        return ready

    def _probe(self, endpoint: str) -> bool:
        url = f'http://{endpoint}{self.spec.readiness_path}'

        def attempt() -> bool:
            chaos.inject('serve.probe', service=self.service_name,
                         endpoint=endpoint)
            with urllib.request.urlopen(
                    url, timeout=_PROBE_TIMEOUT_S) as resp:
                if not 200 <= resp.status < 400:
                    raise resilience.TransientError(
                        f'readiness returned {resp.status}')
                return True

        try:
            return resilience.retry_transient(
                attempt,
                max_attempts=_PROBE_ATTEMPTS,
                transient=(Exception,),
                backoff=common_utils.Backoff(initial=0.2, cap=1.0,
                                             jitter=0.2))
        except Exception:  # pylint: disable=broad-except
            return False

    def _cluster_alive(self, cluster_name: str) -> bool:
        from skypilot_tpu import core as core_lib
        record = core_lib.refresh_cluster_status(cluster_name)
        return record is not None

    def ready_endpoints(self) -> List[str]:
        return [r['endpoint'] for r in self.replicas()
                if r['status'] == serve_state.ReplicaStatus.READY and
                r['endpoint'] and not r['draining']]

    def serving_endpoints(self, mode: str = 'rolling',
                          target: int = 1) -> List[str]:
        """Endpoints the LB should route to under the update mode.

        rolling: every READY replica (old + new mix while rolling).
        blue_green (reference autoscalers.py:323): traffic stays on
        the OLD fleet until >= target new-version replicas are READY,
        then cuts over to the new fleet in one step (the old fleet is
        drained by reconcile_versions right after).

        Draining replicas are excluded in both modes: a drain means
        'stop admitting' the moment it starts.
        """
        if mode != 'blue_green':
            return self.ready_endpoints()
        ready = [r for r in self.replicas()
                 if r['status'] == serve_state.ReplicaStatus.READY and
                 r['endpoint'] and not r['draining']]
        old_ready = [r for r in ready if r['version'] < self.version]
        new_ready = [r for r in ready if r['version'] == self.version]
        if old_ready and len(new_ready) < max(1, target):
            return [r['endpoint'] for r in old_ready]
        return [r['endpoint'] for r in new_ready]

    def recover_preempted(self) -> None:
        """Replace PREEMPTED replicas (spot recovery for serving)."""
        with self._lock:
            live = self.replicas()
            # Replicas that left by another path (scale-down, version
            # reconcile) must not leak detection timestamps — a reused
            # replica id would report a bogus multi-hour latency.
            live_ids = {r['replica_id'] for r in live}
            for rid in list(self._preempted_at):
                if rid not in live_ids:
                    del self._preempted_at[rid]
            for rid in list(self._replica_placement):
                if rid not in live_ids:
                    del self._replica_placement[rid]
            for rid in list(self._draining):
                if rid not in live_ids:
                    del self._draining[rid]
            for r in live:
                if r['status'] == serve_state.ReplicaStatus.PREEMPTED:
                    from skypilot_tpu.utils import tracing
                    serve_state.remove_replica(self.service_name,
                                               r['replica_id'])
                    with tracing.span('serve.recover_replica',
                                      service=self.service_name,
                                      replica=r['replica_id']):
                        new_id = self._start_replica(spot=r['spot'])
                    preempted_at = self._preempted_at.pop(
                        r['replica_id'], None)
                    global_state.record_recovery_event(
                        'replica.relaunched',
                        scope=(f'service/{self.service_name}/replica/'
                               f'{r["replica_id"]}'),
                        cause='preemption',
                        latency_s=(time.time() - preempted_at
                                   if preempted_at is not None else None),
                        detail={'replacement_replica': new_id})
                    from skypilot_tpu.utils import remediation
                    remediation.record_resolved(
                        scope=f'service/{self.service_name}',
                        detector='preemption',
                        ident=f'replica/{r["replica_id"]}',
                        action='recover_replica',
                        detail={'replacement_replica': new_id})
