"""Generate the Hyperbolic catalog CSV (twin of
sky/catalog/data_fetchers/fetch_hyperbolic.py in role).

With a key + egress, rows come from the marketplace listing; offline
the checked-in CSV is a static snapshot of typical marketplace offers.
Single 'marketplace' pseudo-region; terminate-only; no spot market.

Run: python -m skypilot_tpu.catalog.data_fetchers.fetch_hyperbolic
"""
from __future__ import annotations

import csv
import os
from typing import List, Tuple

# (itype `<count>x-<MODEL>`, acc, count, vcpus, mem, acc_mem, price)
_SKUS: List[Tuple[str, str, float, float, float, float, float]] = [
    ('1x-H100-SXM', 'H100-SXM', 1, 24, 128, 80, 1.49),
    ('8x-H100-SXM', 'H100-SXM', 8, 192, 1024, 640, 11.92),
    ('1x-A100-80GB', 'A100-80GB', 1, 16, 96, 80, 0.99),
    ('8x-A100-80GB', 'A100-80GB', 8, 128, 768, 640, 7.92),
    ('1x-RTX4090', 'RTX4090', 1, 8, 32, 24, 0.35),
    ('4x-RTX4090', 'RTX4090', 4, 32, 128, 96, 1.40),
]

HEADER = ['InstanceType', 'AcceleratorName', 'AcceleratorCount', 'vCPUs',
          'MemoryGiB', 'AcceleratorMemoryGiB', 'Price', 'SpotPrice',
          'Region', 'AvailabilityZone']


def rows_static() -> List[List[str]]:
    return [[itype, acc, f'{count:g}', f'{vcpus:g}', f'{mem:g}',
             f'{acc_mem:g}', f'{price:.4f}', '0', 'marketplace',
             'marketplace']
            for itype, acc, count, vcpus, mem, acc_mem, price in _SKUS]


def main() -> None:
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, 'data', 'hyperbolic', 'catalog.csv')
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, 'w', newline='', encoding='utf-8') as f:
        writer = csv.writer(f)
        writer.writerow(HEADER)
        writer.writerows(rows_static())
    print(f'Wrote {path} (static snapshot)')


if __name__ == '__main__':
    main()
