"""Audit / kill framework daemon processes (round-end hygiene sweep).

Detached daemons are by-design during operation (the API server, serve
controllers, and gang job runners survive their parents). But at a
round boundary — snapshot time, bench capture, CI teardown — NOTHING
framework-owned should still be running: a survivor chews the machine
and, worst case, holds the TPU chip and zeroes the next benchmark
capture ("UNAVAILABLE" at backend init).

This is deliberately a scorched-earth sweep: it finds EVERY live
framework process (healthy or leaked — it does not consult cluster or
service records) and, in kill mode, takes them all down. Do not run
``--kill`` while workloads you care about are still running.

Usage:
  python -m skypilot_tpu.utils.reaper            # report only
  python -m skypilot_tpu.utils.reaper --kill     # TERM, then KILL
  xsky reap [--kill]                             # same via the CLI
"""
from __future__ import annotations

import os
import signal
import time
from typing import Dict, List, Optional, Sequence

# Substrings that mark a process as framework-owned. Gang job commands
# and serve replicas run under a job_runner session, so killing the
# runner's group takes its tree down with it.
FRAMEWORK_PATTERNS: Sequence[str] = (
    'skypilot_tpu.agent.job_runner',
    'skypilot_tpu.agent.daemon',
    'skypilot_tpu.serve.controller',
    'skypilot_tpu.server.app',
)


def _cmdline(pid: int) -> Optional[str]:
    try:
        with open(f'/proc/{pid}/cmdline', 'rb') as f:
            return f.read().replace(b'\0', b' ').decode(
                'utf-8', errors='replace')
    except OSError:
        return None


def _ancestors(pid: int) -> List[int]:
    out = []
    for _ in range(64):
        try:
            with open(f'/proc/{pid}/stat', encoding='utf-8') as f:
                fields = f.read().rsplit(')', 1)[-1].split()
            ppid = int(fields[1])
        except (OSError, IndexError, ValueError):
            break
        if ppid <= 1:
            break
        out.append(ppid)
        pid = ppid
    return out


def find_framework_processes(
        patterns: Sequence[str] = FRAMEWORK_PATTERNS
) -> List[Dict[str, object]]:
    """Live framework processes (excluding this process's own tree, so
    a sweep run from inside a launch doesn't eat itself)."""
    self_tree = {os.getpid(), *_ancestors(os.getpid())}
    found = []
    for entry in os.listdir('/proc'):
        if not entry.isdigit():
            continue
        pid = int(entry)
        if pid in self_tree:
            continue
        cmd = _cmdline(pid)
        if not cmd:
            continue
        if any(p in cmd for p in patterns):
            found.append({'pid': pid, 'cmdline': cmd.strip()})
    return found


# Back-compat alias (some callers read better with this name).
find_leaked = find_framework_processes


def reap(patterns: Sequence[str] = FRAMEWORK_PATTERNS,
         grace_s: float = 5.0) -> List[Dict[str, object]]:
    """TERM each framework process's session, escalate to KILL.

    Returns the swept records, each with ``killed`` (gone by return
    time) — a False there (e.g. PermissionError on someone else's
    process) means the sweep did NOT clear the machine.
    """
    swept = find_framework_processes(patterns)
    for rec in swept:
        pid = int(rec['pid'])  # type: ignore[arg-type]
        try:
            # Runners start their children in their own session: signal
            # the group so the whole tree goes.
            os.killpg(pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                os.kill(pid, signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                continue
    deadline = time.time() + grace_s
    while time.time() < deadline:
        if not find_framework_processes(patterns):
            break
        time.sleep(0.2)
    for rec in find_framework_processes(patterns):
        pid = int(rec['pid'])  # type: ignore[arg-type]
        try:
            os.killpg(pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                os.kill(pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
    still_alive = {int(r['pid'])  # type: ignore[arg-type]
                   for r in find_framework_processes(patterns)}
    for rec in swept:
        rec['killed'] = int(rec['pid']) not in still_alive  # type: ignore
    return swept


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    import json
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--kill', action='store_true',
                        help='signal the framework processes (default: '
                             'report only)')
    args = parser.parse_args(argv)
    if args.kill:
        swept = reap()
        for rec in swept:
            print(json.dumps(rec))
        survivors = [r for r in swept if not r.get('killed')]
        if survivors:
            print(f'# {len(survivors)} framework processes survived '
                  'the sweep')
            return 1
    else:
        for rec in find_framework_processes():
            print(json.dumps(rec))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
