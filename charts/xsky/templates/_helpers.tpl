{{- define "xsky.fullname" -}}
{{- printf "%s" .Release.Name | trunc 63 | trimSuffix "-" -}}
{{- end -}}

{{- define "xsky.labels" -}}
app.kubernetes.io/name: xsky
app.kubernetes.io/instance: {{ .Release.Name }}
app.kubernetes.io/managed-by: {{ .Release.Service }}
{{- end -}}

{{- define "xsky.selectorLabels" -}}
app.kubernetes.io/name: xsky
app.kubernetes.io/instance: {{ .Release.Name }}
{{- end -}}
