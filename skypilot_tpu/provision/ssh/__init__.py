"""SSH node-pool provisioner."""
