"""Audit / kill framework daemon processes (round-end hygiene sweep).

Detached daemons are by-design during operation (the API server, serve
controllers, and gang job runners survive their parents). But at a
round boundary — snapshot time, bench capture, CI teardown — NOTHING
framework-owned should still be running: a survivor chews the machine
and, worst case, holds the TPU chip and zeroes the next benchmark
capture ("UNAVAILABLE" at backend init).

The sweep finds EVERY live framework process and annotates each as
``owned`` (a live cluster/job/service/server record claims it) vs
``leaked`` (nothing in the control plane knows it exists). Kill mode
stays deliberately scorched-earth by default — do not run ``--kill``
while workloads you care about are still running; ``--leaked-only``
is the surgical variant that spares record-owned processes.

Usage:
  python -m skypilot_tpu.utils.reaper                  # report (annotated)
  python -m skypilot_tpu.utils.reaper --kill           # TERM, then KILL all
  python -m skypilot_tpu.utils.reaper --kill --leaked-only
  xsky reap [--kill] [--leaked-only]                   # same via the CLI
"""
from __future__ import annotations

import os
import signal
import time
from typing import Dict, List, Optional, Sequence

# Substrings that mark a process as framework-owned. Gang job commands
# and serve replicas run under a job_runner session, so killing the
# runner's group takes its tree down with it.
FRAMEWORK_PATTERNS: Sequence[str] = (
    'skypilot_tpu.agent.job_runner',
    'skypilot_tpu.agent.daemon',
    'skypilot_tpu.jobs.controller',
    'skypilot_tpu.serve.controller',
    'skypilot_tpu.server.app',
)


def _cmdline(pid: int) -> Optional[str]:
    try:
        with open(f'/proc/{pid}/cmdline', 'rb') as f:
            return f.read().replace(b'\0', b' ').decode(
                'utf-8', errors='replace')
    except OSError:
        return None


def _ancestors(pid: int) -> List[int]:
    out = []
    for _ in range(64):
        try:
            with open(f'/proc/{pid}/stat', encoding='utf-8') as f:
                fields = f.read().rsplit(')', 1)[-1].split()
            ppid = int(fields[1])
        except (OSError, IndexError, ValueError):
            break
        if ppid <= 1:
            break
        out.append(ppid)
        pid = ppid
    return out


def find_framework_processes(
        patterns: Sequence[str] = FRAMEWORK_PATTERNS
) -> List[Dict[str, object]]:
    """Live framework processes (excluding this process's own tree, so
    a sweep run from inside a launch doesn't eat itself)."""
    self_tree = {os.getpid(), *_ancestors(os.getpid())}
    found = []
    for entry in os.listdir('/proc'):
        if not entry.isdigit():
            continue
        pid = int(entry)
        if pid in self_tree:
            continue
        cmd = _cmdline(pid)
        if not cmd:
            continue
        if any(p in cmd for p in patterns):
            found.append({'pid': pid, 'cmdline': cmd.strip()})
    return found


# Back-compat alias (some callers read better with this name).
find_leaked = find_framework_processes


# ---- record-aware ownership ------------------------------------------------
# `xsky reap` report mode annotates each process as `owned` (a live
# cluster/job/service/server record claims it) vs `leaked` (nothing in
# the control plane knows it exists). --kill stays scorched-earth;
# --leaked-only kills only what no record owns.


def _proc_environ(pid: int) -> Dict[str, str]:
    try:
        with open(f'/proc/{pid}/environ', 'rb') as f:
            raw = f.read()
    except OSError:
        return {}
    out = {}
    for chunk in raw.split(b'\0'):
        if b'=' in chunk:
            k, _, v = chunk.partition(b'=')
            out[k.decode('utf-8', 'replace')] = v.decode('utf-8',
                                                         'replace')
    return out


def _trailing_arg(cmd: str, marker: str) -> Optional[str]:
    """The first argv token after `-m <marker>` (job id / service)."""
    tokens = cmd.split()
    try:
        idx = tokens.index(marker)
    except ValueError:
        return None
    return tokens[idx + 1] if len(tokens) > idx + 1 else None


def _live_host_roots() -> List[str]:
    """host_root dirs of every recorded (non-torn-down) cluster — the
    record-side truth agent daemons/job runners are matched against."""
    from skypilot_tpu import state
    roots = []
    for record in state.get_clusters():
        info = getattr(record.get('handle'), 'cluster_info', None)
        for inst in getattr(info, 'instances', {}).values():
            root = (getattr(inst, 'tags', None) or {}).get('host_root')
            if root:
                roots.append(root)
    return roots


def _owner_of(pid: int, cmd: str,
              host_roots: Sequence[str]) -> Optional[str]:
    """Which record owns this process, or None (= leaked).

    `host_roots` is the precomputed cluster-host truth (one state scan
    for the whole sweep, not one per process). All lookups read the
    local state DBs — errors propagate to classify(), which fails
    closed (marks the process owned).
    """
    if 'skypilot_tpu.jobs.controller' in cmd:
        from skypilot_tpu.jobs import state as jobs_state
        arg = _trailing_arg(cmd, 'skypilot_tpu.jobs.controller')
        try:
            job = jobs_state.get_job(int(arg))
        except (TypeError, ValueError):
            return None
        if job is not None and not job['status'].is_terminal() and \
                job['controller_pid'] == pid:
            return f'job/{job["job_id"]}'
        return None
    if 'skypilot_tpu.serve.controller' in cmd:
        from skypilot_tpu.serve import state as serve_state
        name = _trailing_arg(cmd, 'skypilot_tpu.serve.controller')
        record = serve_state.get_service(name) if name else None
        if record is not None and record['controller_pid'] == pid and \
                record['status'] != serve_state.ServiceStatus.FAILED:
            return f'service/{name}'
        return None
    if 'skypilot_tpu.server.app' in cmd:
        from skypilot_tpu.server import app as server_app
        try:
            with open(server_app.pid_file(), encoding='utf-8') as f:
                recorded = int(f.readline().strip())
        except (FileNotFoundError, ValueError):
            # No/corrupt pid file: genuinely unrecorded → leaked.
            return None
        # Other OSErrors (e.g. PermissionError: CLI running under a
        # different home than the server) propagate to classify()'s
        # fail-closed handler — an unreadable record must spare the
        # process, not condemn it.
        return 'api-server' if recorded == pid else None
    # Agent daemons / job runners: owned when their cluster root (from
    # the process env) sits inside a recorded cluster's host dir.
    cluster_root = _proc_environ(pid).get('XSKY_CLUSTER_ROOT')
    if cluster_root:
        for root in host_roots:
            if cluster_root == root or \
                    cluster_root.startswith(root.rstrip('/') + '/'):
                return f'cluster-host:{root}'
    return None


def classify(procs: Optional[List[Dict[str, object]]] = None
             ) -> List[Dict[str, object]]:
    """Annotate framework processes with ``owned``/``owner``.

    Fails CLOSED: if the record lookup itself errors (sqlite busy,
    corrupt DB), the process is marked owned — `--leaked-only` exists
    to spare record-owned workloads, and a transient DB error must
    never turn it into a workload kill.
    """
    if procs is None:
        procs = find_framework_processes()
    host_roots: Optional[List[str]] = None
    for rec in procs:
        try:
            if host_roots is None:
                host_roots = _live_host_roots()
            owner = _owner_of(int(rec['pid']),  # type: ignore[arg-type]
                              str(rec['cmdline']), host_roots)
            owned = owner is not None
        except Exception as e:  # pylint: disable=broad-except
            owner = f'unknown (record check failed: {e})'
            owned = True
        rec['owner'] = owner
        rec['owned'] = owned
    return procs


def _signal_tree(pid: int, sig: int) -> None:
    """Signal the process's session group (runners start children in
    their own session), falling back to the single pid."""
    try:
        os.killpg(pid, sig)
    except (ProcessLookupError, PermissionError, OSError):
        try:
            os.kill(pid, sig)
        except (ProcessLookupError, PermissionError):
            pass


def reap(patterns: Sequence[str] = FRAMEWORK_PATTERNS,
         grace_s: float = 5.0,
         leaked_only: bool = False) -> List[Dict[str, object]]:
    """TERM each targeted framework process's session, escalate to KILL.

    Default: scorched-earth over every framework process (round-end
    hygiene). With ``leaked_only``, processes a live record owns are
    spared — the surgical mode for reclaiming strays on a machine with
    workloads still running.

    Returns the swept records, each with ``killed`` (gone by return
    time) — a False there (e.g. PermissionError on someone else's
    process) means the sweep did NOT clear the targets.
    """
    swept = classify(find_framework_processes(patterns))
    if leaked_only:
        swept = [r for r in swept if not r['owned']]
    selected = {int(r['pid']) for r in swept}  # type: ignore[arg-type]

    def _targets() -> set:
        """Scorched-earth re-finds every framework process each pass —
        one spawned mid-sweep (e.g. by a not-yet-dead reconciler) must
        still die, or it holds the chip into the next benchmark run.
        leaked-only stays pinned to the classified set: a process that
        appeared mid-sweep was never classified and must be spared."""
        found = {int(r['pid'])  # type: ignore[arg-type]
                 for r in find_framework_processes(patterns)}
        return (selected & found) if leaked_only else found

    for pid in _targets():
        _signal_tree(pid, signal.SIGTERM)
    deadline = time.time() + grace_s
    while time.time() < deadline:
        if not _targets():
            break
        time.sleep(0.2)
    for pid in _targets():
        _signal_tree(pid, signal.SIGKILL)
    survivors = find_framework_processes(patterns)
    still_alive = {int(r['pid']) for r in survivors}  # type: ignore
    if not leaked_only:
        # Late arrivals belong in the report (killed=False makes the
        # sweep exit nonzero rather than lie that the machine is clean).
        known = {int(r['pid']) for r in swept}  # type: ignore[arg-type]
        swept.extend(r for r in survivors
                     if int(r['pid']) not in known)  # type: ignore
    else:
        still_alive &= selected
    for rec in swept:
        rec['killed'] = int(rec['pid']) not in still_alive  # type: ignore
    return swept


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse
    import json
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--kill', action='store_true',
                        help='signal the framework processes (default: '
                             'report only)')
    parser.add_argument('--leaked-only', action='store_true',
                        help='restrict to processes no cluster/job/'
                             'service/server record owns')
    args = parser.parse_args(argv)
    if args.kill:
        swept = reap(leaked_only=args.leaked_only)
        for rec in swept:
            print(json.dumps(rec))
        survivors = [r for r in swept if not r.get('killed')]
        if survivors:
            print(f'# {len(survivors)} framework processes survived '
                  'the sweep')
            return 1
    else:
        for rec in classify():
            if args.leaked_only and rec['owned']:
                continue
            print(json.dumps(rec))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
