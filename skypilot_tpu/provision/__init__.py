"""Provisioner dispatch (twin of sky/provision/__init__.py:41-211).

Each cloud implements a module ``skypilot_tpu.provision.<name>.instance``
exporting the op-set below; calls route by cloud name. All ops are
idempotent with respect to cluster_name tags.
"""
from __future__ import annotations

import importlib
from typing import Any, Dict, List, Optional

from skypilot_tpu.provision import common


def _impl(provider_name: str):
    return importlib.import_module(
        f'skypilot_tpu.provision.{provider_name}.instance')


def run_instances(provider_name: str, region: str, zone: Optional[str],
                  cluster_name: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    return _impl(provider_name).run_instances(region, zone, cluster_name,
                                              config)


def stop_instances(provider_name: str, cluster_name: str,
                   provider_config: Dict[str, Any]) -> None:
    _impl(provider_name).stop_instances(cluster_name, provider_config)


def terminate_instances(provider_name: str, cluster_name: str,
                        provider_config: Dict[str, Any]) -> None:
    _impl(provider_name).terminate_instances(cluster_name, provider_config)


def query_instances(provider_name: str, cluster_name: str,
                    provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    """instance_id → status (None if terminated)."""
    return _impl(provider_name).query_instances(cluster_name,
                                                provider_config)


def wait_instances(provider_name: str, region: str, cluster_name: str,
                   state: str,
                   provider_config: Optional[Dict[str, Any]] = None) -> None:
    _impl(provider_name).wait_instances(region, cluster_name, state,
                                        provider_config=provider_config)


def get_cluster_info(provider_name: str, region: str,
                     cluster_name: str,
                     provider_config: Optional[Dict[str, Any]] = None
                     ) -> common.ClusterInfo:
    return _impl(provider_name).get_cluster_info(region, cluster_name,
                                                 provider_config or {})


def open_ports(provider_name: str, cluster_name: str, ports: List[str],
               provider_config: Dict[str, Any]) -> None:
    impl = _impl(provider_name)
    if hasattr(impl, 'open_ports'):
        impl.open_ports(cluster_name, ports, provider_config)


def cleanup_ports(provider_name: str, cluster_name: str,
                  provider_config: Dict[str, Any]) -> None:
    impl = _impl(provider_name)
    if hasattr(impl, 'cleanup_ports'):
        impl.cleanup_ports(cluster_name, provider_config)


def query_ports(provider_name: str, cluster_name: str,
                ports: List[str],
                provider_config: Dict[str, Any],
                cluster_info: common.ClusterInfo
                ) -> Dict[int, str]:
    """port → reachable endpoint URL (twin of the reference's
    query_ports op backing `sky status --endpoint`).

    Providers with indirection (kubernetes NodePort) implement their
    own; the default maps each requested port onto the head host's
    feasible IP — correct wherever open_ports exposed the port on the
    instance itself (firewall/security-group clouds).
    """
    impl = _impl(provider_name)
    if hasattr(impl, 'query_ports'):
        return impl.query_ports(cluster_name, ports, provider_config,
                                cluster_info)
    head = cluster_info.get_head_instance()
    if head is None:
        return {}
    ip = head.get_feasible_ip()
    out: Dict[int, str] = {}
    for spec in ports or []:
        spec = str(spec)
        lo, _, hi = spec.partition('-')
        for port in range(int(lo), int(hi or lo) + 1):
            out[port] = f'http://{ip}:{port}'
    return out
