"""Int8 weight-only quantization (ops/quantization.py).

Parity is asserted against the bf16 path for all four families' serve
stacks plus the slot engine end-to-end; the HBM claim (half the bytes)
is asserted on the quantized pytree directly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.ops import quantization as qops


class TestQuantizedTensor:

    def test_roundtrip_error_bound(self):
        w = jax.random.normal(jax.random.PRNGKey(0), (64, 32),
                              jnp.float32)
        qt = qops.quantize(w)
        back = qops.dequantize(qt, jnp.float32)
        # Symmetric int8: per-channel error ≤ scale/2 = max|w|/254.
        err = jnp.abs(back - w)
        bound = jnp.max(jnp.abs(w), axis=0) / 254 + 1e-6
        assert bool(jnp.all(err <= bound[None, :]))

    def test_matmul_parity(self):
        k1, k2 = jax.random.split(jax.random.PRNGKey(1))
        x = jax.random.normal(k1, (4, 64), jnp.float32)
        w = jax.random.normal(k2, (64, 32), jnp.float32)
        exact = x @ w
        approx = qops.matmul(x, qops.quantize(w))
        rel = (jnp.linalg.norm(approx - exact) /
               jnp.linalg.norm(exact))
        assert float(rel) < 0.01
        # Plain arrays pass through exactly.
        np.testing.assert_array_equal(np.asarray(qops.matmul(x, w)),
                                      np.asarray(exact))

    def test_embed_rows_parity(self):
        table = jax.random.normal(jax.random.PRNGKey(2), (100, 16),
                                  jnp.float32)
        qt = qops.quantize(table, axis=-1)
        tokens = jnp.array([3, 7, 99])
        exact = table[tokens]
        approx = qops.embed_rows(qt, tokens)
        assert float(jnp.max(jnp.abs(approx - exact))) < 0.02
        np.testing.assert_array_equal(
            np.asarray(qops.embed_rows(table, tokens)),
            np.asarray(exact))

    def test_scan_slices_stay_paired(self):
        """A stacked [L, in, out] QuantizedTensor scans layer-by-layer
        (q and scale slice together; axis=-2 stays valid)."""
        w = jax.random.normal(jax.random.PRNGKey(3), (3, 16, 8),
                              jnp.float32)
        qt = qops.quantize(w)
        x = jax.random.normal(jax.random.PRNGKey(4), (2, 16), jnp.float32)

        def body(carry, layer_w):
            return carry, qops.matmul(x, layer_w)

        _, outs = jax.lax.scan(body, 0, qt)
        assert outs.shape == (3, 2, 8)
        exact = jnp.einsum('bi,lio->lbo', x, w)
        rel = jnp.linalg.norm(outs - exact) / jnp.linalg.norm(exact)
        assert float(rel) < 0.01

    def test_quantize_params_structure_and_bytes(self):
        from skypilot_tpu.models import llama
        c = llama.LLAMA_TINY
        params = llama.init(c, jax.random.PRNGKey(0))
        qparams = qops.quantize_params(params)
        # Norms stay full precision; weights become QuantizedTensor.
        assert isinstance(qparams['layers']['wq'], qops.QuantizedTensor)
        assert isinstance(qparams['embed'], qops.QuantizedTensor)
        assert qparams['embed'].axis == -1
        assert not isinstance(qparams['layers']['attn_norm'],
                              qops.QuantizedTensor)
        assert not isinstance(qparams['final_norm'],
                              qops.QuantizedTensor)
        # ~half the HBM (int8 vs bf16; scales are a rounding error).
        ratio = (qops.params_nbytes(qparams) /
                 qops.params_nbytes(params))
        assert 0.45 < ratio < 0.62
        # Idempotent.
        again = qops.quantize_params(qparams)
        assert again['layers']['wq'] is qparams['layers']['wq']


def _family_logits(model_lib, config, params, tokens):
    """Serve-path logits: prefill_hidden → lm_logits."""
    hidden, _ = model_lib.prefill_hidden(
        config, params, tokens, jnp.int32(tokens.shape[1]))
    return model_lib.lm_logits(config, params, hidden)


@pytest.mark.parametrize('family', ['llama', 'qwen', 'gemma', 'moe'])
def test_family_serve_parity(family):
    """Quantized-weight logits track bf16 logits closely enough that
    greedy decoding is unaffected on a random tiny model."""
    from skypilot_tpu import models as models_pkg
    from skypilot_tpu.models import gemma, llama, moe, qwen
    cfg = {'llama': llama.LLAMA_TINY, 'qwen': qwen.QWEN_TINY,
           'gemma': gemma.GEMMA_TINY, 'moe': moe.MOE_TINY}[family]
    model_lib = models_pkg.module_for(cfg)
    params = model_lib.init(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                cfg.vocab_size)
    exact = _family_logits(model_lib, cfg, params, tokens)
    approx = _family_logits(model_lib, cfg,
                            qops.quantize_params(params), tokens)
    rel = (jnp.linalg.norm(approx - exact) /
           jnp.linalg.norm(exact))
    assert float(rel) < 0.05, f'{family}: rel logit error {rel}'


def test_synthetic_quantized_params_serve():
    """The bench's direct-to-int8 initializer (no bf16 tree is ever
    materialized) produces a tree the serve path runs on."""
    import functools
    from skypilot_tpu.models import llama
    cfg = llama.LLAMA_TINY
    shapes = jax.eval_shape(functools.partial(llama.init, cfg),
                            jax.random.PRNGKey(0))
    params = qops.synthetic_quantized_params(shapes, jax.random.PRNGKey(1))
    assert isinstance(params['layers']['wq'], qops.QuantizedTensor)
    assert params['layers']['wq'].q.dtype == jnp.int8
    # Same tree structure as a real init (so sharding rules etc. apply).
    real = jax.tree_util.tree_structure(
        qops.quantize_params(llama.init(cfg, jax.random.PRNGKey(0))))
    assert jax.tree_util.tree_structure(params) == real
    tokens = jnp.zeros((1, 8), jnp.int32)
    logits = _family_logits(llama, cfg, params, tokens)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_engine_int8_weights_decode_parity():
    """End-to-end slot engine: int8 weights produce the same greedy
    tokens as bf16 weights on a tiny model."""
    from skypilot_tpu.infer import engine as engine_lib
    from skypilot_tpu.models import llama

    cfg_model = llama.LLAMA_TINY
    params = llama.init(cfg_model, jax.random.PRNGKey(0))
    prompt = list(range(2, 10))

    def greedy_tokens(weight_dtype):
        config = engine_lib.EngineConfig(
            model=cfg_model, max_slots=2, max_target_len=64,
            prefill_buckets=(16,), weight_dtype=weight_dtype)
        engine = engine_lib.InferenceEngine(config, params)
        state = engine.init_decode_state()
        first, kv, true_len = engine.prefill(jnp.array(prompt))
        state = engine.insert(state, kv, first, true_len, slot=0)
        out = [int(jax.device_get(first))]
        for _ in range(8):
            state, sampled = engine.decode_step(state)
            out.append(int(jax.device_get(sampled[0])))
        return out

    bf16 = greedy_tokens(jnp.bfloat16)
    int8 = greedy_tokens(jnp.int8)
    # Random tiny models have near-flat logits, so allow one divergence
    # step; on real checkpoints the margin is far larger.
    agree = sum(a == b for a, b in zip(bf16, int8))
    assert agree >= len(bf16) - 1, (bf16, int8)
