"""`service:` YAML section (twin of sky/serve/service_spec.py:422)."""
from __future__ import annotations

from typing import Any, Dict, Optional


class SLOSpec:
    """The service's declared objectives (``slo:`` subsection)::

        slo:
          ttft_p99_ms: 500      # p99 time-to-first-token at the LB
          availability: 0.999   # non-error fraction of requests
          tpot_p50_ms: 40       # median inter-token latency (replica)
          deadline_ms: 30000    # per-request end-to-end deadline

    All fields optional; burn rates are computed per declared objective
    (serve/slo.py). The error budget falls out of each objective: a
    p99 target concedes 1% of requests, a p50 target 50%, and
    availability concedes ``1 - availability``.

    ``deadline_ms`` is not a burn objective: the LB relays each
    request's remaining budget to the replica
    (``X-Xsky-Deadline-S``), and the orchestrator rejects a deferred
    request at admit when that budget can no longer cover its
    estimated prefill+decode cost — shedding doomed work instead of
    finishing it late (journalled as ``serve.deadline_reject``).
    """

    FIELDS = ('ttft_p99_ms', 'availability', 'tpot_p50_ms',
              'deadline_ms')

    def __init__(self, ttft_p99_ms: Optional[float] = None,
                 availability: Optional[float] = None,
                 tpot_p50_ms: Optional[float] = None,
                 deadline_ms: Optional[float] = None) -> None:
        if ttft_p99_ms is not None and ttft_p99_ms <= 0:
            raise ValueError('slo.ttft_p99_ms must be > 0')
        if tpot_p50_ms is not None and tpot_p50_ms <= 0:
            raise ValueError('slo.tpot_p50_ms must be > 0')
        if deadline_ms is not None and deadline_ms <= 0:
            raise ValueError('slo.deadline_ms must be > 0')
        if availability is not None and not 0.0 < availability <= 1.0:
            raise ValueError(
                'slo.availability must be in (0, 1] (a fraction, '
                'not a percentage)')
        if ttft_p99_ms is None and availability is None and \
                tpot_p50_ms is None and deadline_ms is None:
            raise ValueError(
                'slo: declares no objective; expected at least one of '
                f'{list(self.FIELDS)}')
        self.ttft_p99_ms = \
            float(ttft_p99_ms) if ttft_p99_ms is not None else None
        self.availability = \
            float(availability) if availability is not None else None
        self.tpot_p50_ms = \
            float(tpot_p50_ms) if tpot_p50_ms is not None else None
        self.deadline_ms = \
            float(deadline_ms) if deadline_ms is not None else None

    @classmethod
    def from_config(cls, config: Optional[Dict[str, Any]]
                    ) -> Optional['SLOSpec']:
        if not config:
            return None
        config = dict(config)
        kwargs = {field: config.pop(field, None)
                  for field in cls.FIELDS}
        if config:
            raise ValueError(
                f'Unknown slo fields: {sorted(config)}; expected a '
                f'subset of {list(cls.FIELDS)}.')
        return cls(**kwargs)

    def to_config(self) -> Dict[str, Any]:
        return {field: getattr(self, field) for field in self.FIELDS
                if getattr(self, field) is not None}


class SkyServiceSpec:

    def __init__(self,
                 readiness_path: str = '/',
                 initial_delay_seconds: float = 60.0,
                 min_replicas: int = 1,
                 max_replicas: Optional[int] = None,
                 target_qps_per_replica: Optional[float] = None,
                 upscale_delay_seconds: float = 300.0,
                 downscale_delay_seconds: float = 1200.0,
                 replica_port: Optional[int] = None,
                 use_ondemand_fallback: bool = False,
                 base_ondemand_fallback_replicas: int = 0,
                 dynamic_ondemand_fallback: bool = False,
                 load_balancing_policy: str = 'round_robin',
                 tls_certfile: Optional[str] = None,
                 tls_keyfile: Optional[str] = None,
                 slo: Optional[SLOSpec] = None,
                 autoscaler: Optional[str] = None) -> None:
        if bool(tls_certfile) != bool(tls_keyfile):
            raise ValueError(
                'tls requires BOTH certfile and keyfile')
        if max_replicas is not None and max_replicas < min_replicas:
            raise ValueError('max_replicas must be >= min_replicas')
        if target_qps_per_replica is not None and max_replicas is None:
            raise ValueError(
                'autoscaling (target_qps_per_replica) requires '
                'max_replicas')
        if autoscaler is not None and autoscaler not in (
                'request_rate', 'burn_rate'):
            raise ValueError(
                f'Unknown autoscaler {autoscaler!r}; expected '
                "'request_rate' or 'burn_rate'.")
        if autoscaler == 'burn_rate':
            if slo is None:
                raise ValueError(
                    'autoscaler: burn_rate requires an slo: section '
                    '(burn rates are computed per declared objective)')
            if max_replicas is None:
                raise ValueError(
                    'autoscaler: burn_rate requires max_replicas')
        if base_ondemand_fallback_replicas < 0:
            raise ValueError(
                'base_ondemand_fallback_replicas must be >= 0')
        from skypilot_tpu.serve import load_balancing_policies as lb_pol
        if load_balancing_policy not in lb_pol.POLICIES:
            raise ValueError(
                f'Unknown load_balancing_policy '
                f'{load_balancing_policy!r}; expected one of '
                f'{sorted(lb_pol.POLICIES)}.')
        self.readiness_path = readiness_path
        self.initial_delay_seconds = initial_delay_seconds
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.target_qps_per_replica = target_qps_per_replica
        self.upscale_delay_seconds = upscale_delay_seconds
        self.downscale_delay_seconds = downscale_delay_seconds
        self.replica_port = replica_port
        self.use_ondemand_fallback = use_ondemand_fallback
        # Mixed spot/on-demand fleets (twin of the reference's
        # FallbackRequestRateAutoscaler knobs): keep N replicas always
        # on-demand, and/or cover not-ready spot replicas with
        # temporary on-demand ones.
        self.base_ondemand_fallback_replicas = \
            base_ondemand_fallback_replicas
        self.dynamic_ondemand_fallback = dynamic_ondemand_fallback
        self.load_balancing_policy = load_balancing_policy
        # TLS termination at the load balancer (twin of the reference's
        # service-spec `tls:` section → HTTPS endpoint).
        self.tls_certfile = tls_certfile
        self.tls_keyfile = tls_keyfile
        # Declared objectives; None = no burn-rate evaluation (the SLO
        # monitor still records latency digests for `xsky slo`).
        self.slo = slo
        # Which autoscaler drives target_replicas: None picks by knobs
        # (target_qps_per_replica → request_rate, else fixed);
        # 'burn_rate' scales on the SLO monitor's multi-window burn.
        self.autoscaler = autoscaler

    @property
    def tls_enabled(self) -> bool:
        return self.tls_certfile is not None

    @property
    def autoscaling_enabled(self) -> bool:
        return self.target_qps_per_replica is not None

    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any]) -> 'SkyServiceSpec':
        config = dict(config or {})
        readiness = config.pop('readiness_probe', '/')
        if isinstance(readiness, str):
            readiness_path, initial_delay = readiness, 60.0
        else:
            readiness_path = readiness.get('path', '/')
            initial_delay = float(
                readiness.get('initial_delay_seconds', 60))
        policy = config.pop('replica_policy', None)
        if policy is None:
            replicas = config.pop('replicas', 1)
            policy = {'min_replicas': replicas, 'max_replicas': None}
        port = config.pop('port', None)
        lb_policy = config.pop('load_balancing_policy', 'round_robin')
        tls = config.pop('tls', None) or {}
        slo = SLOSpec.from_config(config.pop('slo', None))
        unknown = set(config)
        if unknown:
            raise ValueError(f'Unknown service fields: {sorted(unknown)}')
        return cls(
            readiness_path=readiness_path,
            initial_delay_seconds=initial_delay,
            min_replicas=int(policy.get('min_replicas', 1)),
            max_replicas=(int(policy['max_replicas'])
                          if policy.get('max_replicas') is not None
                          else None),
            target_qps_per_replica=policy.get('target_qps_per_replica'),
            upscale_delay_seconds=float(
                policy.get('upscale_delay_seconds', 300)),
            downscale_delay_seconds=float(
                policy.get('downscale_delay_seconds', 1200)),
            replica_port=int(port) if port is not None else None,
            use_ondemand_fallback=bool(
                policy.get('use_ondemand_fallback', False)),
            base_ondemand_fallback_replicas=int(
                policy.get('base_ondemand_fallback_replicas', 0)),
            dynamic_ondemand_fallback=bool(
                policy.get('dynamic_ondemand_fallback', False)),
            load_balancing_policy=lb_policy,
            tls_certfile=tls.get('certfile'),
            tls_keyfile=tls.get('keyfile'),
            slo=slo,
            autoscaler=policy.get('autoscaler'),
        )

    def to_yaml_config(self) -> Dict[str, Any]:
        config: Dict[str, Any] = {
            'readiness_probe': {
                'path': self.readiness_path,
                'initial_delay_seconds': self.initial_delay_seconds,
            },
            'replica_policy': {
                'min_replicas': self.min_replicas,
            },
        }
        policy = config['replica_policy']
        if self.max_replicas is not None:
            policy['max_replicas'] = self.max_replicas
        if self.target_qps_per_replica is not None:
            policy['target_qps_per_replica'] = self.target_qps_per_replica
            policy['upscale_delay_seconds'] = self.upscale_delay_seconds
            policy['downscale_delay_seconds'] = \
                self.downscale_delay_seconds
        if self.autoscaler is not None:
            policy['autoscaler'] = self.autoscaler
            policy.setdefault('downscale_delay_seconds',
                              self.downscale_delay_seconds)
        if self.use_ondemand_fallback:
            policy['use_ondemand_fallback'] = True
        if self.base_ondemand_fallback_replicas:
            policy['base_ondemand_fallback_replicas'] = \
                self.base_ondemand_fallback_replicas
        if self.dynamic_ondemand_fallback:
            policy['dynamic_ondemand_fallback'] = True
        if self.replica_port is not None:
            config['port'] = self.replica_port
        if self.load_balancing_policy != 'round_robin':
            config['load_balancing_policy'] = self.load_balancing_policy
        if self.tls_enabled:
            config['tls'] = {'certfile': self.tls_certfile,
                             'keyfile': self.tls_keyfile}
        if self.slo is not None:
            config['slo'] = self.slo.to_config()
        return config
