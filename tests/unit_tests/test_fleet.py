"""Fleet scheduler tests: fair-share admission (weights, aging,
starvation bound), placement-score decay + backfill tolerance, the
elastic shrink/grow-back state machine, the shared-scorer consumers
(spot placer ranking, launch blocklist), the bounded fleet_decisions
table, gang-exclude renumbering, CLI surfaces, and the tier-1
`tools/bench_fleet.py --smoke` subprocess gate (chaos preemption storm:
elastic recovery must beat the full-relaunch baseline on goodput, with
journalled, trace-linked gang_shrunk → gang_regrown)."""
import json
import os
import subprocess
import sys
import time

import pytest

from skypilot_tpu.jobs import fleet

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), '..', '..'))


@pytest.fixture
def tmp_state(monkeypatch, tmp_path):
    from skypilot_tpu import state
    monkeypatch.setenv('XSKY_STATE_DB', str(tmp_path / 'state.db'))
    state.reset_for_test()
    yield state
    state.reset_for_test()


@pytest.fixture
def tmp_jobs(monkeypatch, tmp_path):
    from skypilot_tpu.jobs import state as jobs_state
    monkeypatch.setenv('XSKY_JOBS_DB', str(tmp_path / 'jobs.db'))
    yield jobs_state


# ---- fair-share admission ---------------------------------------------------


class TestFairShare:

    def _row(self, job_id, workspace='default', priority=0, age_s=0.0,
             now=1000.0):
        return {'job_id': job_id, 'workspace': workspace,
                'priority': priority, 'submitted_at': now - age_s}

    def test_underserved_workspace_wins(self, monkeypatch):
        monkeypatch.delenv('XSKY_FLEET_SHARES', raising=False)
        waiting = [self._row(1, 'busy'), self._row(2, 'idle')]
        picked = fleet.pick_next(waiting, {'busy': 3, 'idle': 0},
                                 now=1000.0)
        assert picked == 2

    def test_weights_shift_the_share(self, monkeypatch):
        # busy runs 4, idle runs 1 — but busy's weight is 8, so its
        # usage 4/8 is BELOW idle's 1/1: busy's head wins.
        monkeypatch.setenv('XSKY_FLEET_SHARES', 'busy=8')
        waiting = [self._row(1, 'busy'), self._row(2, 'idle')]
        picked = fleet.pick_next(waiting, {'busy': 4, 'idle': 1},
                                 now=1000.0)
        assert picked == 1

    def test_priority_wins_within_workspace(self):
        waiting = [self._row(1, priority=0), self._row(2, priority=5)]
        assert fleet.pick_next(waiting, {}, now=1000.0) == 2

    def test_fifo_tiebreak(self):
        waiting = [self._row(2), self._row(1)]
        assert fleet.pick_next(waiting, {}, now=1000.0) == 1

    def test_aging_overcomes_priority_within_workspace(
            self, monkeypatch):
        """The starvation bound: a prio-0 job waiting longer than
        (prio gap) x XSKY_FLEET_AGING_S outranks a fresh high-prio
        head of its own workspace."""
        monkeypatch.setenv('XSKY_FLEET_AGING_S', '10')
        old_low = self._row(1, priority=0, age_s=51.0)   # aged +5.1
        fresh_high = self._row(2, priority=5, age_s=0.0)
        assert fleet.pick_next([old_low, fresh_high], {},
                               now=1000.0) == 1
        # One second under the bound: priority still wins.
        young_low = self._row(1, priority=0, age_s=49.0)
        assert fleet.pick_next([young_low, fresh_high], {},
                               now=1000.0) == 2

    def test_aging_overcomes_share_penalty_across_workspaces(
            self, monkeypatch):
        monkeypatch.setenv('XSKY_FLEET_AGING_S', '10')
        monkeypatch.setenv('XSKY_FLEET_SHARE_PENALTY', '1.0')
        # busy's head has waited: aged score 0 + 31/10 - 3 > idle's 0.
        waiting = [self._row(1, 'busy', age_s=31.0),
                   self._row(2, 'idle')]
        assert fleet.pick_next(waiting, {'busy': 3}, now=1000.0) == 1

    def test_shares_env_parsing(self, monkeypatch):
        monkeypatch.setenv('XSKY_FLEET_SHARES',
                           'prod=4, research=2,bad,junk=x,zero=0')
        assert fleet.workspace_shares() == {'prod': 4.0,
                                            'research': 2.0}

    def test_claim_next_waiting_claims_and_records(
            self, tmp_state, tmp_jobs, monkeypatch):
        monkeypatch.delenv('XSKY_FLEET_SHARES', raising=False)
        jobs_state = tmp_jobs
        a = jobs_state.add_job('a', {}, workspace='busy')
        b = jobs_state.add_job('b', {}, workspace='idle')
        for jid in (a, b):
            jobs_state.set_schedule_state(
                jid, jobs_state.ScheduleState.WAITING)
        # busy already holds capacity.
        c = jobs_state.add_job('c', {}, workspace='busy')
        jobs_state.set_schedule_state(c,
                                      jobs_state.ScheduleState.ALIVE)
        picked = fleet.claim_next_waiting()
        assert picked == b
        record = jobs_state.get_job(b)
        assert record['schedule_state'] is \
            jobs_state.ScheduleState.LAUNCHING
        decisions = tmp_state.get_fleet_decisions(kind='admit')
        assert decisions and decisions[0]['job_id'] == b
        assert decisions[0]['workspace'] == 'idle'
        assert decisions[0]['score'] is not None
        # Next claim takes the remaining head.
        assert fleet.claim_next_waiting() == a
        assert fleet.claim_next_waiting() is None

    def test_scheduler_uses_fair_share(self, tmp_state, tmp_jobs,
                                       monkeypatch):
        """maybe_schedule_next_jobs spawns the fair-share pick, not
        the FIFO head."""
        from skypilot_tpu.jobs import scheduler
        jobs_state = tmp_jobs
        spawned = []
        monkeypatch.setattr(scheduler, '_spawn_controller',
                            spawned.append)
        monkeypatch.setenv('XSKY_JOBS_MAX_LAUNCHING', '1')
        busy = jobs_state.add_job('busy-job', {}, workspace='busy')
        idle = jobs_state.add_job('idle-job', {}, workspace='idle')
        running = jobs_state.add_job('running', {}, workspace='busy')
        jobs_state.set_schedule_state(
            running, jobs_state.ScheduleState.ALIVE)
        jobs_state.set_controller_pid(running, os.getpid())
        for jid in (busy, idle):
            jobs_state.set_schedule_state(
                jid, jobs_state.ScheduleState.WAITING)
        scheduler.maybe_schedule_next_jobs()
        assert spawned == [idle]


# ---- placement scoring ------------------------------------------------------


class TestPlacementScore:

    def _event(self, age_s, now=1000.0, **keys):
        return {'ts': now - age_s, 'event_type': 'job.preempted',
                'detail': keys or None}

    def test_decay_halves_per_window(self):
        now = 1000.0
        pm = fleet.PressureMap(
            [self._event(0, zone='z1'), self._event(60, zone='z1')],
            now=now, half_life_s=60.0)
        assert pm.at(zone='z1') == pytest.approx(1.5)
        assert pm.at(zone='z2') == 0.0

    def test_backfill_tolerant(self):
        """Rows that predate structured keys (no detail / prose-only
        detail / partial keys) score only what they carry."""
        now = 1000.0
        events = [
            {'ts': now, 'event_type': 'job.preempted', 'detail': None},
            {'ts': now, 'event_type': 'job.preempted',
             'detail': {'cluster': 'c1'}},               # prose-only
            {'ts': now, 'event_type': 'failover.blocked',
             'detail': {'zone': 'z1'}},                  # partial
            {'ts': now, 'event_type': 'failover.blocked',
             'detail': {'cloud': 'fake', 'region': 'r1', 'zone': 'z1',
                        'sku': 'tpu-v5e-32'}},
        ]
        pm = fleet.PressureMap(events, now=now, half_life_s=60.0)
        assert pm.at(zone='z1') == pytest.approx(2.0)
        assert pm.at(cloud='fake') == pytest.approx(1.0)
        # Querying a field the partial event doesn't define must not
        # drop the fully-keyed match.
        assert pm.at(zone='z1', sku='tpu-v5e-32') == pytest.approx(2.0)

    def test_zone_pressures_scores_hot_zone(self, tmp_state):
        tmp_state.record_recovery_event(
            'replica.preempted', scope='service/s/replica/1',
            detail={'zone': 'z-hot', 'cloud': 'fake'})
        pressures = fleet.zone_pressures(['z-hot', 'z-cold'])
        assert pressures['z-hot'] > pressures['z-cold'] == 0.0

    def test_zone_pressures_never_raises_without_db(self, monkeypatch,
                                                    tmp_path):
        from skypilot_tpu import state
        monkeypatch.setenv('XSKY_STATE_DB',
                           str(tmp_path / 'nested' / 'state.db'))
        state.reset_for_test()
        try:
            assert fleet.zone_pressures(['b', 'a']) == \
                {'a': 0.0, 'b': 0.0}
        finally:
            state.reset_for_test()

    def test_spot_placer_uses_shared_scorer(self, tmp_state):
        from skypilot_tpu.serve import spot_placer as placer_lib
        tmp_state.record_recovery_event(
            'job.preempted', scope='job/1',
            detail={'zone': 'z1', 'cloud': 'fake'})
        placer = placer_lib.SpotPlacer(['z1', 'z2'])
        assert placer.select_zone() == 'z2'
        # The in-memory preemptive set still applies on top.
        placer.handle_preemption('z2')
        assert placer.select_zone() == 'z1'

    def test_placement_blocks_spot_scoped_and_capped(
            self, tmp_state, monkeypatch):
        from skypilot_tpu import Resources, Task
        monkeypatch.setenv('XSKY_FLEET_BLOCK_THRESHOLD', '0.5')
        for i in range(6):
            tmp_state.record_recovery_event(
                'job.preempted', scope='job/1',
                detail={'cloud': 'fake', 'zone': f'z{i}',
                        'sku': 'tpu-v5e-32'})
        spot = Task('t', run='true')
        spot.set_resources(Resources(accelerators='tpu-v5e-32',
                                     use_spot=True))
        blocks = fleet.placement_blocks(spot)
        assert blocks and len(blocks) <= 4
        for b in blocks:
            assert b.zone is not None
            assert (b.accelerator_args or {}).get(
                'provisioning_model') == 'spot'
        ondemand = Task('t', run='true')
        ondemand.set_resources(Resources(accelerators='tpu-v5e-32'))
        assert fleet.placement_blocks(ondemand) == []

    def test_capacity_ok_after_decay(self, monkeypatch):
        monkeypatch.setenv('XSKY_FLEET_BLOCK_THRESHOLD', '0.6')
        now = 1000.0
        event = {'ts': now - 30, 'event_type': 'job.gang_shrunk',
                 'detail': {'zone': 'z1'}}
        hot = fleet.PressureMap([event], now=now, half_life_s=60.0)
        cold = fleet.PressureMap([event], now=now + 60,
                                 half_life_s=60.0)
        assert hot.at(zone='z1') >= 0.6
        assert cold.at(zone='z1') < 0.6

    def test_sku_of(self):
        from skypilot_tpu import Resources
        assert fleet.sku_of(
            Resources(accelerators='tpu-v5e-32')) == 'tpu-v5e-32'
        assert fleet.sku_of(Resources()) is None


# ---- elastic gang state machine ---------------------------------------------


class TestElasticGang:

    def test_can_shrink_gates(self, monkeypatch):
        gang = fleet.ElasticGang(full_hosts=4)
        assert gang.can_shrink([2])
        assert not gang.can_shrink([0])       # head rank must survive
        assert not gang.can_shrink([])
        # Floor: 4 hosts at 0.5 ⇒ at least 2 survivors.
        assert not gang.can_shrink([1, 2, 3])
        assert gang.can_shrink([1, 2])
        monkeypatch.setenv('XSKY_FLEET_ELASTIC', '0')
        assert not gang.can_shrink([2])
        monkeypatch.delenv('XSKY_FLEET_ELASTIC')
        assert not fleet.ElasticGang(full_hosts=1).can_shrink([0])

    def test_shrink_growback_regrow_cycle(self, monkeypatch):
        monkeypatch.setenv('XSKY_FLEET_GROWBACK_S', '10')
        gang = fleet.ElasticGang(full_hosts=4)
        excluded = gang.shrink([2], now=100.0)
        assert excluded == {2}
        assert gang.state == fleet.STATE_SHRUNK
        assert gang.survivors == 3
        assert gang.generation == 1
        assert not gang.growback_due(now=105.0)
        assert gang.growback_due(now=110.0)
        # Deferral re-arms the probe but keeps the true shrink time.
        gang.defer_growback(now=110.0)
        assert not gang.growback_due(now=115.0)
        assert gang.growback_due(now=120.0)
        assert gang.shrunk_at == 100.0
        gang.regrow()
        assert gang.state == fleet.STATE_FULL
        assert gang.generation == 2
        assert not gang.growback_due(now=1000.0)

    def test_repeated_shrink_respects_floor(self):
        gang = fleet.ElasticGang(full_hosts=4)
        gang.shrink([3], now=100.0)
        # Another rank dies while shrunk: 2 survivors = floor, ok...
        assert gang.can_shrink([2])
        gang.shrink([2], now=101.0)
        # ...but a third would go below it.
        assert not gang.can_shrink([1])
        # Re-reported already-excluded ranks never shrink twice.
        assert not gang.can_shrink([2, 3])

    def test_detail_round_trip(self):
        gang = fleet.ElasticGang(full_hosts=4)
        gang.shrink([1, 3], now=42.0)
        restored = fleet.ElasticGang.from_detail(
            json.loads(json.dumps(gang.to_detail())), full_hosts=4)
        assert restored.excluded == {1, 3}
        assert restored.shrunk_at == 42.0
        assert restored.generation == 1
        assert restored.full_hosts == 4
        assert restored.next_probe_at == gang.next_probe_at

    def test_reset_on_full_relaunch(self):
        gang = fleet.ElasticGang(full_hosts=4)
        gang.shrink([2])
        gang.reset(full_hosts=8)
        assert gang.state == fleet.STATE_FULL
        assert gang.full_hosts == 8
        assert gang.excluded == set()


class TestGangExclude:
    """The agent-side half of a shrink: exclude_hosts renumbers ranks
    contiguously over the survivors (new world size, new coordinator
    when needed — the jax.distributed remesh contract)."""

    def _cluster(self, n=4):
        from skypilot_tpu.provision import common as pc
        instances = {
            f'h{i}': pc.InstanceInfo(
                instance_id=f'h{i}', internal_ip=f'10.0.0.{i + 1}',
                external_ip=None, status='RUNNING',
                tags={'node_index': '0'}, slice_id='slice-a',
                host_index=i)
            for i in range(n)
        }
        return pc.ClusterInfo(instances=instances,
                              head_instance_id='h0',
                              provider_name='fake')

    def test_exclude_renumbers_contiguously(self):
        from skypilot_tpu.agent import gang
        envs = gang.build_host_envs(self._cluster(4),
                                    exclude_hosts=[2])
        assert len(envs) == 3
        assert [e['XSKY_HOST_RANK'] for e in envs] == ['0', '1', '2']
        for env in envs:
            assert env['XSKY_NUM_HOSTS'] == '3'
        # Survivors are hosts 0, 1, 3; the ex-host-3 is now rank 2.
        assert envs[2]['TPU_WORKER_HOSTNAMES'].count('10.0.0.3') == 0
        # TPU worker ids must index contiguously into the survivor-only
        # hostnames list — not keep the provision-time host_index
        # (ex-host-3 would claim id 3 against a 3-entry list and wedge
        # libtpu bring-up on the shrunk incarnation).
        assert [e['TPU_WORKER_ID'] for e in envs] == ['0', '1', '2']
        for env in envs:
            assert len(env['TPU_WORKER_HOSTNAMES'].split(',')) == 3

    def test_exclude_empty_is_identity(self):
        from skypilot_tpu.agent import gang
        full = gang.build_host_envs(self._cluster(2))
        again = gang.build_host_envs(self._cluster(2),
                                     exclude_hosts=[])
        assert full == again


# ---- fleet_decisions table --------------------------------------------------


class TestFleetDecisions:

    def test_round_trip_and_filters(self, tmp_state):
        tmp_state.record_fleet_decisions([
            {'kind': 'admit', 'job_id': 1, 'workspace': 'w',
             'score': 1.5, 'detail': {'priority': 2}},
            {'kind': 'shrink', 'job_id': 1, 'cluster': 'c',
             'zone': 'z1', 'sku': 'tpu-v5e-32'},
        ])
        rows = tmp_state.get_fleet_decisions()
        assert [r['kind'] for r in rows] == ['shrink', 'admit']
        assert rows[1]['detail'] == {'priority': 2}
        assert tmp_state.get_fleet_decisions(kind='admit')[0][
            'score'] == 1.5
        assert tmp_state.get_fleet_decisions(job_id=1, limit=1,
                                             offset=1)[0][
            'kind'] == 'admit'

    def test_retention_prune(self, tmp_state, monkeypatch):
        monkeypatch.setattr(tmp_state, '_MAX_FLEET_DECISIONS', 5)
        # Fresh process-equivalent: the first-batch prune keys on the
        # process-local insert counter.
        monkeypatch.setattr(tmp_state, '_fleet_decision_inserts', 0)
        # One batch (prune runs on the FIRST batch, like every bounded
        # table — short-lived CLI writers never reach an amortized
        # gate): only the newest 5 survive.
        tmp_state.record_fleet_decisions(
            [{'kind': f'k{i}'} for i in range(12)])
        rows = tmp_state.get_fleet_decisions(limit=100)
        assert len(rows) == 5
        assert rows[0]['kind'] == 'k11'
        assert rows[-1]['kind'] == 'k7'

    def test_never_raises(self, tmp_state, monkeypatch):
        monkeypatch.setattr(tmp_state, '_get_conn',
                            lambda: (_ for _ in ()).throw(
                                RuntimeError('db down')))
        tmp_state.record_fleet_decisions([{'kind': 'admit'}])
        fleet.record_decision('admit', job_id=1)


# ---- CLI surfaces -----------------------------------------------------------


class TestCLI:

    def test_fleet_command_json(self, tmp_state, tmp_jobs):
        from click.testing import CliRunner

        from skypilot_tpu.client import cli as cli_mod
        jid = tmp_jobs.add_job('q', {}, workspace='w', priority=3)
        tmp_jobs.set_schedule_state(
            jid, tmp_jobs.ScheduleState.WAITING)
        tmp_state.record_fleet_decisions(
            [{'kind': 'shrink', 'job_id': jid, 'zone': 'z1',
              'score': 0.9}])
        tmp_state.record_recovery_event(
            'job.preempted', scope=f'job/{jid}',
            detail={'zone': 'z1', 'cloud': 'fake'})
        result = CliRunner().invoke(cli_mod.cli, ['fleet', '--json'])
        assert result.exit_code == 0, result.output
        payload = json.loads(result.output)
        assert payload['queue'].get('waiting') == 1
        assert any(w['workspace'] == 'w' and w['waiting'] == 1
                   for w in payload['workspaces'])
        assert any(p.get('zone') == 'z1' for p in payload['pressure'])
        assert payload['decisions'][0]['kind'] == 'shrink'

    def test_jobs_queue_columns(self, tmp_state, tmp_jobs):
        from click.testing import CliRunner

        from skypilot_tpu.client import cli as cli_mod
        jid = tmp_jobs.add_job('shrunky', {}, priority=7)
        tmp_jobs.set_status(jid, tmp_jobs.ManagedJobStatus.RUNNING)
        tmp_jobs.set_gang_state(jid, 'SHRUNK',
                                {'full_hosts': 4, 'excluded': [2]})
        result = CliRunner().invoke(cli_mod.cli, ['jobs', 'queue'])
        assert result.exit_code == 0, result.output
        assert 'PRIO' in result.output and 'GANG' in result.output
        row = next(l for l in result.output.splitlines()
                   if 'shrunky' in l)
        assert ' 7 ' in row
        assert '3/4' in row

    def test_metrics_fleet_gauges(self, tmp_state, tmp_jobs):
        from skypilot_tpu.server import metrics as server_metrics
        jid = tmp_jobs.add_job('g', {})
        tmp_jobs.set_schedule_state(jid,
                                    tmp_jobs.ScheduleState.WAITING)
        tmp_jobs.set_status(jid, tmp_jobs.ManagedJobStatus.RUNNING)
        tmp_jobs.set_gang_state(jid, 'SHRUNK', {'full_hosts': 2,
                                                'excluded': [1]})
        text = server_metrics.render()
        assert 'xsky_fleet_queue_depth{state="waiting"} 1' in text
        assert 'xsky_fleet_gangs_shrunk 1' in text


# ---- priority plumbing ------------------------------------------------------


class TestPriorityPlumbing:

    def test_add_job_persists_priority(self, tmp_jobs):
        jid = tmp_jobs.add_job('p', {}, priority=9)
        assert tmp_jobs.get_job(jid)['priority'] == 9
        assert tmp_jobs.get_waiting_jobs() == []   # not WAITING yet
        tmp_jobs.set_schedule_state(jid,
                                    tmp_jobs.ScheduleState.WAITING)
        rows = tmp_jobs.get_waiting_jobs()
        assert rows[0]['priority'] == 9

    def test_jobs_launch_payload_accepts_priority(self):
        from skypilot_tpu.server import payloads
        run, kwargs = payloads._VERBS['jobs.launch'](  # pylint: disable=protected-access
            {'task': {'name': 't', 'run': 'true'}, 'name': 't',
             'priority': 4})
        del run
        assert kwargs['priority'] == 4


# ---- elastic batch accommodation (train/launch.py) --------------------------


class TestElasticBatch:

    def test_divisible_unchanged(self, monkeypatch):
        from skypilot_tpu.train import launch as train_launch
        monkeypatch.delenv('XSKY_ELASTIC_GENERATION', raising=False)
        assert train_launch.per_host_batch(8, 4) == 2

    def test_non_divisible_raises_outside_elastic(self, monkeypatch):
        from skypilot_tpu.train import launch as train_launch
        monkeypatch.delenv('XSKY_ELASTIC_GENERATION', raising=False)
        with pytest.raises(ValueError):
            train_launch.per_host_batch(8, 3)

    def test_elastic_rounds_down(self, monkeypatch):
        from skypilot_tpu.train import launch as train_launch
        monkeypatch.setenv('XSKY_ELASTIC_GENERATION', '1')
        assert train_launch.per_host_batch(8, 3) == 2


# ---- tier-1 acceptance: the chaos preemption storm gate ---------------------


class TestBenchFleetSmoke:
    """Tier-1 acceptance (ISSUE 10): under the same chaos preemption
    storm (stalled rank + provisioning capacity drought) on the fake
    cloud, elastic fleet recovery must achieve strictly higher goodput
    than the forced full-relaunch baseline, with journalled,
    trace-linked job.gang_shrunk → job.gang_regrown transitions and a
    scored grow-back decision."""

    def test_bench_fleet_smoke_gate(self):
        env = dict(os.environ)
        env['JAX_PLATFORMS'] = 'cpu'
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO_ROOT, 'tools', 'bench_fleet.py'),
             '--smoke'],
            capture_output=True, text=True, timeout=420, env=env,
            check=False)
        line = next((l for l in proc.stdout.splitlines()
                     if l.startswith('{')), '{}')
        result = json.loads(line)
        assert proc.returncode == 0, \
            f'bench_fleet gate failed:\n{proc.stdout}\n{proc.stderr}'
        assert result['pass'] is True
        assert all(result['gates'].values()), result['gates']
        assert result['elastic']['goodput'] > \
            result['baseline']['goodput']
        assert result['shrink_latency_s'] > 0
        assert result['regrow_after_s'] > 0
