"""Bounded-concurrency host fan-out for the control plane.

Every per-host step of cluster bring-up (volume mounts, wheel
bootstrap, docker init, task setup, workdir/file-mount sync) used to
run sequentially, so launch latency grew O(num_hosts) — a v5p-512
slice (64 hosts) paid ~64× the single-host cost before the gang even
started. :func:`run_in_parallel` is the one fan-out primitive those
loops now share (twin of the reference's subprocess_utils.run_in_parallel,
sky/utils/subprocess_utils.py, thread-pool based because the per-item
work is subprocess/ssh-bound, not CPU-bound):

  * **Ordered results** — ``results[i]`` is ``fn(args[i])`` no matter
    which rank finished first.
  * **Gang-shaped failure** — the first failure stops new ranks from
    starting (in-flight ones finish so their stderr is complete) and
    every failure is aggregated into ONE
    :class:`~skypilot_tpu.exceptions.MultiHostError` naming each
    failed rank, not just the first.
  * **Whole-phase deadline** — a :class:`resilience.Deadline` bounds
    the phase; on expiry, queued ranks are cancelled and still-running
    stragglers are recorded as ``DeadlineExceeded`` failures (their
    threads are abandoned, not joined — the subprocesses they drive
    are the caller's to reap).
  * **Chaos** — each rank traverses the ``fanout.worker`` point with
    ``{'phase': ..., 'rank': ...}`` context, so fault tests can fail
    or delay individual ranks mid-fan-out
    (``{"match": {"phase": "setup", "rank": 1}, "error": ...}``).
  * **Tracing** — the whole phase runs inside a ``fanout.<phase>``
    span and each rank inside a ``fanout.<phase>.rank`` child
    (utils/tracing; `xsky trace` renders the waterfall and flags the
    slowest rank + stragglers; per-rank timings feed the
    ``xsky_fanout_*`` metrics). Each rank also emits a
    ``timeline.Event`` named ``fanout.<phase>`` carrying its
    ``trace_id``; with ``XSKY_TIMELINE_FILE`` set the Chrome trace
    shows per-phase concurrency (overlapping bars across tids).

Concurrency is bounded by ``max_workers`` (default
``$XSKY_FANOUT_WORKERS``, 16): enough to hide per-host ssh latency
without hitting sshd's MaxStartups or the local fd ceiling at pod
scale. ``XSKY_FANOUT_WORKERS=1`` degenerates to the old sequential
loops exactly: ranks run in order and the first failure aborts before
the next rank starts.
"""
from __future__ import annotations

import os
import queue
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.utils import chaos
from skypilot_tpu.utils import metrics
from skypilot_tpu.utils import resilience
from skypilot_tpu.utils import timeline
from skypilot_tpu.utils import tracing

logger = sky_logging.init_logger(__name__)

ENV_FANOUT_WORKERS = 'XSKY_FANOUT_WORKERS'
DEFAULT_FANOUT_WORKERS = 16


def fanout_workers() -> int:
    """The configured fan-out width (``$XSKY_FANOUT_WORKERS``, ≥1)."""
    raw = os.environ.get(ENV_FANOUT_WORKERS, '').strip()
    if not raw:
        return DEFAULT_FANOUT_WORKERS
    try:
        return max(1, int(raw))
    except ValueError:
        logger.warning(
            f'Ignoring non-integer {ENV_FANOUT_WORKERS}={raw!r}; '
            f'using {DEFAULT_FANOUT_WORKERS}.')
        return DEFAULT_FANOUT_WORKERS


def run_in_parallel(fn: Callable[[Any], Any],
                    args: Iterable[Any],
                    *,
                    max_workers: Optional[int] = None,
                    deadline: Optional[resilience.Deadline] = None,
                    phase: str = 'fanout',
                    what: Optional[str] = None) -> List[Any]:
    """Run ``fn`` over ``args`` with bounded concurrency.

    Returns ``[fn(a) for a in args]`` in input order. Raises
    :class:`exceptions.MultiHostError` aggregating every failed rank
    when any item fails or the deadline expires (gang semantics: a
    failure cancels ranks that have not started yet; in-flight ranks
    finish so their errors/stderr are complete).

    Args:
        fn: per-item callable; its index is the item's "rank".
        args: the items (materialized once; may be any iterable).
        max_workers: concurrency bound; defaults to
            ``$XSKY_FANOUT_WORKERS`` (16). ``1`` is exactly the old
            sequential for-loop (in-order, abort before next rank).
        deadline: whole-phase budget. Queued ranks are cancelled on
            expiry; running stragglers become ``DeadlineExceeded``
            entries in the raised ``MultiHostError``.
        phase: short name for chaos/timeline context ('bootstrap',
            'setup', ...).
        what: human phase description for error messages (defaults to
            ``phase``).
    """
    items = list(args)
    total = len(items)
    if total == 0:
        return []
    what = what or phase
    if max_workers is None:
        max_workers = fanout_workers()
    workers = max(1, min(int(max_workers), total))
    deadline = deadline or resilience.Deadline.unlimited()
    # Whole-phase span: rank spans parent under it, so `xsky trace`
    # shows the fan-out as one bar with per-rank children (and the
    # slowest rank called out). With tracing disabled this is the
    # no-op singleton and nothing below allocates for observability.
    with tracing.span(f'fanout.{phase}', hosts=total,
                      workers=workers) as fanout_span:
        return _fanout(fn, items, total, workers, deadline, phase,
                       what, fanout_span)


def _fanout(fn: Callable[[Any], Any], items: List[Any], total: int,
            workers: int, deadline: resilience.Deadline, phase: str,
            what: str, fanout_span: Any) -> List[Any]:
    results: List[Any] = [None] * total
    failures: Dict[int, BaseException] = {}
    not_started: List[int] = []
    # What the raise at the bottom reads. The parallel branch fills
    # these with snapshots: abandoned stragglers keep mutating
    # `failures`/`not_started` (they close over the names), so raising
    # from those dicts directly could hit "dict changed size during
    # iteration" inside MultiHostError.
    final_failures: Dict[int, BaseException] = failures
    final_not_started: List[int] = not_started
    # Contextvars do not cross thread spawns: capture the fan-out
    # span's context here (the caller thread, inside the span) and
    # re-attach it per rank. None ⇔ tracing disabled — the rank span
    # is then the no-op singleton and durations are not tracked.
    parent = tracing.capture() if tracing.enabled() else None
    durations: Optional[List[Optional[float]]] = \
        [None] * total if parent is not None else None

    def _one(rank: int, item: Any) -> Any:
        if parent is None:
            with timeline.Event(f'fanout.{phase}', args={'rank': rank}):
                # Chaos rules keyed on phase/rank can fail or delay
                # individual ranks mid-fan-out; an injected raise
                # counts as that rank's failure.
                chaos.inject('fanout.worker', phase=phase, rank=rank)
                return fn(item)
        with tracing.span(f'fanout.{phase}.rank', parent=parent,
                          rank=rank), \
                timeline.Event(f'fanout.{phase}',
                               args={'rank': rank,
                                     'trace_id': parent[0]}):
            # Duration measured around the rank's WORK (chaos delay
            # included — it simulates a slow host), not the span's
            # own serialized DB commit; a failed rank stays None and
            # is not straggler-scored.
            t0 = time.monotonic()
            chaos.inject('fanout.worker', phase=phase, rank=rank)
            result = fn(item)
            durations[rank] = time.monotonic() - t0
            return result

    if workers == 1:
        # Degenerate mode: byte-for-byte the old sequential loops —
        # strict rank order, nothing starts after a failure.
        for rank, item in enumerate(items):
            if failures:
                not_started.append(rank)
                continue
            if deadline.expired:
                failures[rank] = resilience.DeadlineExceeded(
                    f'{what}: deadline expired before host {rank} '
                    'started')
                continue
            try:
                results[rank] = _one(rank, item)
            except Exception as e:  # pylint: disable=broad-except
                failures[rank] = e
    else:
        # Hand-rolled daemon-thread pool, NOT ThreadPoolExecutor: its
        # workers are non-daemon and concurrent.futures joins them at
        # interpreter exit, so one rank hung in a timeout-less ssh
        # would block process exit forever after the deadline already
        # reported it. Daemon workers make "abandon the stragglers"
        # actually true.
        work: 'queue.Queue' = queue.Queue()
        for rank, item in enumerate(items):
            work.put((rank, item))
        cond = threading.Condition()
        running: set = set()
        finished = [0]
        abort = [False]

        def _worker() -> None:
            while True:
                try:
                    rank, item = work.get_nowait()
                except queue.Empty:
                    return
                with cond:
                    if abort[0]:
                        # Gang-shaped abort: a queued rank seen after
                        # a failure never starts.
                        not_started.append(rank)
                        finished[0] += 1
                        cond.notify()
                        continue
                    running.add(rank)
                try:
                    result = _one(rank, item)
                    with cond:
                        results[rank] = result
                except Exception as e:  # pylint: disable=broad-except
                    with cond:
                        failures[rank] = e
                        abort[0] = True
                finally:
                    with cond:
                        running.discard(rank)
                        finished[0] += 1
                        cond.notify()

        threads = [
            threading.Thread(target=_worker, daemon=True,
                             name=f'xsky-fanout-{phase}-{i}')
            for i in range(workers)
        ]
        for t in threads:
            t.start()
        with cond:
            while finished[0] < total:
                if deadline.expired:
                    break
                timeout = (deadline.remaining() if deadline.bounded
                           else None)
                cond.wait(timeout=timeout)
            if finished[0] < total:
                # Budget spent: queued ranks never start, in-flight
                # ranks become DeadlineExceeded failures and their
                # (daemon) threads are abandoned — they cannot block
                # process exit.
                abort[0] = True
                while True:
                    try:
                        rank, _ = work.get_nowait()
                    except queue.Empty:
                        break
                    not_started.append(rank)
                for rank in sorted(running):
                    failures[rank] = resilience.DeadlineExceeded(
                        f'{what}: host {rank} still running at '
                        'deadline')
            # Snapshot under the lock into names the workers never
            # touch — they keep writing into `failures`/`not_started`
            # if they outlive the deadline.
            final_failures = dict(failures)
            final_not_started = list(not_started)

    if durations is not None:
        _observe_ranks(phase, list(durations), fanout_span)
    if final_failures:
        raise exceptions.MultiHostError(what, final_failures, total,
                                        sorted(final_not_started))
    return results


def _observe_ranks(phase: str, durations: List[Optional[float]],
                   fanout_span: Any) -> None:
    """Feed per-rank timings into the metrics registry and flag the
    phase's slowest rank / stragglers on the fan-out span. A straggler
    is a rank slower than 1.5x the phase median — the signal `xsky
    trace` and the `/metrics` straggler ratio both key on."""
    done = [(rank, d) for rank, d in enumerate(durations)
            if d is not None]
    if not done:
        return
    times = sorted(d for _, d in done)
    median = times[len(times) // 2]
    stragglers = [rank for rank, d in done
                  if median > 0 and d > 1.5 * median]
    for _, d in done:
        metrics.observe('xsky_fanout_rank_duration_seconds',
                        'Per-rank fan-out work duration.', d,
                        phase=phase)
    metrics.inc_counter('xsky_fanout_ranks_total',
                        'Fan-out ranks executed.', len(done),
                        phase=phase)
    if stragglers:
        metrics.inc_counter(
            'xsky_fanout_stragglers_total',
            'Ranks slower than 1.5x their phase median.',
            len(stragglers), phase=phase)
    slowest_rank, slowest = max(done, key=lambda rd: rd[1])
    fanout_span.set(slowest_rank=slowest_rank,
                    slowest_s=round(slowest, 6),
                    median_s=round(median, 6),
                    stragglers=stragglers)
