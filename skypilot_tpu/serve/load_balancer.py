"""Load balancer: HTTP proxy → ready replicas (twin of
sky/serve/load_balancer.py:23), stdlib-only like the API server.

Counts requests for the autoscaler (shared via a callback), retries the
next replica on connection failure.
"""
from __future__ import annotations

import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Optional, Tuple

from skypilot_tpu import sky_logging
from skypilot_tpu.serve import load_balancing_policies as lb_policies

logger = sky_logging.init_logger(__name__)

_HOP_HEADERS = {'connection', 'keep-alive', 'transfer-encoding',
                'upgrade', 'proxy-authenticate', 'te', 'trailers',
                'host', 'content-length'}


class SkyServeLoadBalancer:

    def __init__(self, policy: Optional[
            lb_policies.LoadBalancingPolicy] = None,
            on_request: Optional[Callable[[], None]] = None) -> None:
        self.policy = policy or lb_policies.RoundRobinPolicy()
        self.on_request = on_request or (lambda: None)
        self._server: Optional[ThreadingHTTPServer] = None

    def set_ready_replicas(self, endpoints: List[str]) -> None:
        self.policy.set_ready_replicas(endpoints)

    def _proxy(self, method: str, path: str, body: bytes, headers
               ) -> Tuple[int, object, List[Tuple[str, str]],
                          Callable[[], None]]:
        """Returns (status, payload, headers, finish). `payload` is
        either bytes (error bodies) or the OPEN upstream response — the
        handler streams it through chunk-by-chunk so server-sent-event
        responses (/v1 streaming) reach the client as they are
        produced, not after the generation finishes. `finish` must be
        called once the payload is fully relayed (or abandoned): it
        releases the replica's in-flight accounting."""
        self.on_request()
        tried = 0
        max_tries = 3
        while tried < max_tries:
            tried += 1
            replica = self.policy.select_replica()
            if replica is None:
                return (503, b'{"error": "no ready replicas"}', [],
                        lambda: None)
            url = f'http://{replica}{path}'
            req = urllib.request.Request(url, data=body or None,
                                         method=method)
            for k, v in headers.items():
                if k.lower() not in _HOP_HEADERS:
                    req.add_header(k, v)
            try:
                resp = urllib.request.urlopen(req, timeout=120)
            except urllib.error.HTTPError as e:
                self.policy.request_done(replica)
                return e.code, e.read(), [], lambda: None
            except (urllib.error.URLError, OSError, TimeoutError):
                self.policy.request_done(replica)
                continue  # replica unreachable: try another
            out_headers = [(k, v) for k, v in resp.headers.items()
                           if k.lower() not in _HOP_HEADERS]
            # Forward upstream framing: with a Content-Length the
            # client can detect a replica dying mid-body (read1 sees a
            # clean b'' on premature FIN, so the relay itself cannot);
            # SSE responses have none and stay read-until-close.
            upstream_cl = resp.headers.get('Content-Length')
            if upstream_cl is not None:
                out_headers.append(('Content-Length', upstream_cl))
            done = threading.Event()

            def finish(replica=replica, resp=resp, done=done):
                if not done.is_set():  # idempotent
                    done.set()
                    resp.close()
                    self.policy.request_done(replica)

            return resp.status, resp, out_headers, finish
        return (502, b'{"error": "all replicas unreachable"}', [],
                lambda: None)

    def make_server(self, host: str = '0.0.0.0',
                    port: int = 0,
                    certfile: Optional[str] = None,
                    keyfile: Optional[str] = None
                    ) -> ThreadingHTTPServer:
        lb = self

        class _Handler(BaseHTTPRequestHandler):

            def log_message(self, *args):
                pass

            def _handle(self, method: str):
                length = int(self.headers.get('Content-Length') or 0)
                body = self.rfile.read(length) if length else b''
                status, payload, out_headers, finish = lb._proxy(
                    method, self.path, body, self.headers)
                try:
                    self.send_response(status)
                    for k, v in out_headers:
                        self.send_header(k, v)
                    if isinstance(payload, bytes):
                        self.send_header('Content-Length',
                                         str(len(payload)))
                        self.end_headers()
                        self.wfile.write(payload)
                        return
                    # Open upstream response: relay as bytes arrive
                    # (read1 = at most one underlying socket read, so
                    # SSE chunks flush with production latency). No
                    # Content-Length → the client reads until close.
                    self.send_header('Connection', 'close')
                    self.end_headers()
                    while True:
                        try:
                            chunk = payload.read1(65536)
                        except (OSError, TimeoutError):
                            # Replica died mid-body. Headers are already
                            # sent, so no retry is possible — close the
                            # connection so the client sees truncation
                            # rather than a silent clean EOF... which
                            # HTTP/1.0 read-until-close can't express;
                            # log it so the operator can.
                            logger.warning(
                                'upstream replica failed mid-relay on '
                                f'{self.path}')
                            break
                        if not chunk:
                            break
                        self.wfile.write(chunk)
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    pass  # client went away mid-relay
                finally:
                    finish()

            def do_GET(self):  # noqa: N802
                self._handle('GET')

            def do_POST(self):  # noqa: N802
                self._handle('POST')

            def do_PUT(self):  # noqa: N802
                self._handle('PUT')

            def do_DELETE(self):  # noqa: N802
                self._handle('DELETE')

        self._server = ThreadingHTTPServer((host, port), _Handler)
        if certfile:
            # TLS termination at the LB (twin of the reference's
            # service-spec `tls:` → uvicorn ssl kwargs,
            # sky/serve/load_balancer.py:251): replicas stay plain
            # HTTP inside the deployment; clients get HTTPS.
            from skypilot_tpu.utils import tls as tls_utils
            tls_utils.wrap_server_socket(self._server, certfile, keyfile)
        return self._server

    def run_in_thread(self, host: str = '127.0.0.1',
                      port: int = 0,
                      certfile: Optional[str] = None,
                      keyfile: Optional[str] = None) -> int:
        server = self.make_server(host, port, certfile=certfile,
                                  keyfile=keyfile)
        thread = threading.Thread(target=server.serve_forever,
                                  name='xsky-serve-lb', daemon=True)
        thread.start()
        return server.server_address[1]

    def shutdown(self) -> None:
        if self._server is not None:
            self._server.shutdown()
