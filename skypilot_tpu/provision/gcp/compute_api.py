"""Compute Engine v1 REST client — CPU/GPU VMs for controllers & failover.

Twin of GCPComputeInstance (sky/provision/gcp/instance_utils.py:313-1670's
compute half). Controllers (jobs/serve) and GPU failover targets run on
plain VMs; TPU slices go through tpu_api instead.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.provision.gcp import rest
from skypilot_tpu.provision.gcp.tpu_api import (CLUSTER_LABEL, HEAD_LABEL,
                                                cluster_tag)

logger = sky_logging.init_logger(__name__)

BASE = 'https://compute.googleapis.com/compute/v1'

PENDING_STATES = ('PROVISIONING', 'STAGING', 'REPAIRING')
RUNNING_STATE = 'RUNNING'
STOPPING_STATES = ('STOPPING', 'SUSPENDING')
STOPPED_STATES = ('TERMINATED', 'SUSPENDED', 'STOPPED')

DEFAULT_IMAGE = ('projects/ubuntu-os-cloud/global/images/family/'
                 'ubuntu-2204-lts')


class ComputeClient:

    def __init__(self, project: str, zone: str,
                 transport: Optional[rest.Transport] = None) -> None:
        self.project = project
        self.zone = zone
        self.t = transport or rest.Transport()
        self.prefix = f'{BASE}/projects/{project}/zones/{zone}'

    def insert(self, body: Dict[str, Any]) -> Dict[str, Any]:
        return self.t.request('POST', f'{self.prefix}/instances', body=body)

    def get(self, name: str) -> Dict[str, Any]:
        return self.t.request('GET', f'{self.prefix}/instances/{name}')

    def list_cluster(self, cluster_name: str) -> List[Dict[str, Any]]:
        items: List[Dict[str, Any]] = []
        page: Optional[str] = None
        while True:
            params = {'filter': f'labels.{CLUSTER_LABEL}={cluster_name}'}
            if page:
                params['pageToken'] = page
            resp = self.t.request('GET', f'{self.prefix}/instances',
                                  params=params)
            items.extend(resp.get('items', []))
            page = resp.get('nextPageToken')
            if not page:
                break
        return items

    def delete(self, name: str) -> Dict[str, Any]:
        return self.t.request('DELETE', f'{self.prefix}/instances/{name}')

    def stop(self, name: str) -> Dict[str, Any]:
        return self.t.request('POST',
                              f'{self.prefix}/instances/{name}/stop')

    def start(self, name: str) -> Dict[str, Any]:
        return self.t.request('POST',
                              f'{self.prefix}/instances/{name}/start')

    # ---- persistent disks (volumes) ------------------------------------

    def get_disk(self, name: str) -> Optional[Dict[str, Any]]:
        try:
            return self.t.request('GET', f'{self.prefix}/disks/{name}')
        except rest.GcpApiError as e:
            if e.status == 404:
                return None
            raise

    def insert_disk(self, body: Dict[str, Any]) -> Dict[str, Any]:
        return self.t.request('POST', f'{self.prefix}/disks', body=body)

    def delete_disk(self, name: str) -> Dict[str, Any]:
        return self.t.request('DELETE', f'{self.prefix}/disks/{name}')

    def list_disks(self, label_filter: str) -> List[Dict[str, Any]]:
        resp = self.t.request('GET', f'{self.prefix}/disks',
                              params={'filter': label_filter})
        return resp.get('items', [])

    def attach_disk(self, vm_name: str,
                    body: Dict[str, Any]) -> Dict[str, Any]:
        return self.t.request(
            'POST', f'{self.prefix}/instances/{vm_name}/attachDisk',
            body=body)

    def wait_operation(self, op: Dict[str, Any],
                       timeout: float = 900.0,
                       poll_interval: float = 3.0) -> Dict[str, Any]:
        name = op.get('name')
        if not name:
            return op
        deadline = time.time() + timeout
        while time.time() < deadline:
            cur = self.t.request(
                'POST', f'{self.prefix}/operations/{name}/wait')
            if cur.get('status') == 'DONE':
                errors = cur.get('error', {}).get('errors', [])
                if errors:
                    e = errors[0]
                    api_err = rest.GcpApiError(
                        409, e.get('code', ''), e.get('message', ''))
                    raise rest.classify_error(api_err, self.zone)
                return cur
            time.sleep(poll_interval)
        raise exceptions.ProvisionError(
            f'Timed out waiting for compute operation {name}')

    # ---- networks (VPC bootstrap) --------------------------------------

    def get_network(self, name: str) -> Optional[Dict[str, Any]]:
        try:
            return self.t.request(
                'GET', f'{self.global_prefix}/networks/{name}')
        except rest.GcpApiError as e:
            if e.status == 404:
                return None
            raise

    def insert_network(self, body: Dict[str, Any]) -> Dict[str, Any]:
        return self.t.request('POST', f'{self.global_prefix}/networks',
                              body=body)

    # ---- MIG / DWS (GPU flex-start capacity) ---------------------------

    def insert_instance_template(self, body: Dict[str, Any]
                                 ) -> Dict[str, Any]:
        return self.t.request(
            'POST', f'{self.global_prefix}/instanceTemplates', body=body)

    def delete_instance_template(self, name: str) -> Dict[str, Any]:
        return self.t.request(
            'DELETE', f'{self.global_prefix}/instanceTemplates/{name}')

    def insert_mig(self, body: Dict[str, Any]) -> Dict[str, Any]:
        return self.t.request(
            'POST', f'{self.prefix}/instanceGroupManagers', body=body)

    def get_mig(self, name: str) -> Optional[Dict[str, Any]]:
        try:
            return self.t.request(
                'GET', f'{self.prefix}/instanceGroupManagers/{name}')
        except rest.GcpApiError as e:
            if e.status == 404:
                return None
            raise

    def delete_mig(self, name: str) -> Dict[str, Any]:
        return self.t.request(
            'DELETE', f'{self.prefix}/instanceGroupManagers/{name}')

    def insert_resize_request(self, mig: str,
                              body: Dict[str, Any]) -> Dict[str, Any]:
        return self.t.request(
            'POST',
            f'{self.prefix}/instanceGroupManagers/{mig}/resizeRequests',
            body=body)

    def get_resize_request(self, mig: str,
                           name: str) -> Dict[str, Any]:
        return self.t.request(
            'GET', f'{self.prefix}/instanceGroupManagers/{mig}'
                   f'/resizeRequests/{name}')

    def delete_resize_request(self, mig: str,
                              name: str) -> Dict[str, Any]:
        return self.t.request(
            'DELETE', f'{self.prefix}/instanceGroupManagers/{mig}'
                      f'/resizeRequests/{name}')

    def list_managed_instances(self, mig: str) -> List[Dict[str, Any]]:
        out = self.t.request(
            'POST', f'{self.prefix}/instanceGroupManagers/{mig}'
                    '/listManagedInstances')
        return out.get('managedInstances', [])

    # ---- firewalls (global resources; ports exposure) ------------------

    @property
    def global_prefix(self) -> str:
        return f'{BASE}/projects/{self.project}/global'

    def get_firewall(self, name: str) -> Optional[Dict[str, Any]]:
        try:
            return self.t.request(
                'GET', f'{self.global_prefix}/firewalls/{name}')
        except rest.GcpApiError as e:
            if e.status == 404:
                return None
            raise

    def insert_firewall(self, body: Dict[str, Any]) -> Dict[str, Any]:
        return self.t.request('POST', f'{self.global_prefix}/firewalls',
                              body=body)

    def patch_firewall(self, name: str,
                       body: Dict[str, Any]) -> Dict[str, Any]:
        return self.t.request(
            'PATCH', f'{self.global_prefix}/firewalls/{name}', body=body)

    def delete_firewall(self, name: str) -> Dict[str, Any]:
        return self.t.request(
            'DELETE', f'{self.global_prefix}/firewalls/{name}')

    def wait_global_operation(self, op: Dict[str, Any],
                              timeout: float = 300.0,
                              poll_interval: float = 2.0
                              ) -> Dict[str, Any]:
        """Firewalls are global resources; their operations live under
        global/operations, not the zonal endpoint wait_operation polls."""
        name = op.get('name')
        if not name:
            return op
        deadline = time.time() + timeout
        while time.time() < deadline:
            cur = self.t.request(
                'POST',
                f'{self.global_prefix}/operations/{name}/wait')
            if cur.get('status') == 'DONE':
                errors = cur.get('error', {}).get('errors', [])
                if errors:
                    e = errors[0]
                    api_err = rest.GcpApiError(
                        409, e.get('code', ''), e.get('message', ''))
                    raise rest.classify_error(api_err, self.zone)
                return cur
            time.sleep(poll_interval)
        raise exceptions.ProvisionError(
            f'Timed out waiting for global operation {name}')


def firewall_rule_name(cluster_name: str) -> str:
    return f'xsky-{cluster_name}-ports'[:63].rstrip('-')


def firewall_body(cluster_name: str, ports: List[str],
                  network: str) -> Dict[str, Any]:
    """Ingress allow-rule for the cluster's user-requested ports.

    `ports` entries are '80' or '4000-4100' strings (GCP's own
    ports syntax matches Resources' canonical form).
    """
    return {
        'name': firewall_rule_name(cluster_name),
        'network': network,
        'direction': 'INGRESS',
        'allowed': [{
            'IPProtocol': 'tcp',
            'ports': [str(p) for p in ports],
        }],
        'sourceRanges': ['0.0.0.0/0'],
        'targetTags': [cluster_tag(cluster_name)],
    }


def vm_body(node_config: Dict[str, Any], cluster_name: str, vm_name: str,
            zone: str, is_head: bool, node_index: int) -> Dict[str, Any]:
    labels = dict(node_config.get('labels', {}))
    labels[CLUSTER_LABEL] = cluster_name
    labels[HEAD_LABEL] = 'true' if is_head else 'false'
    labels['xsky-node-index'] = str(node_index)
    machine_type = node_config.get('instance_type', 'n2-standard-8')
    body: Dict[str, Any] = {
        'name': vm_name,
        'machineType': f'zones/{zone}/machineTypes/{machine_type}',
        'labels': labels,
        'disks': [{
            'boot': True,
            'autoDelete': True,
            'initializeParams': {
                'sourceImage': node_config.get('image_id', DEFAULT_IMAGE),
                'diskSizeGb': str(node_config.get('disk_size', 256)),
            },
        }],
        'networkInterfaces': [{
            'network': node_config.get('network', 'global/networks/default'),
            'accessConfigs': [{'name': 'External NAT',
                               'type': 'ONE_TO_ONE_NAT'}],
        }],
        'tags': {'items': ['xsky', cluster_tag(cluster_name)]},
        'metadata': {'items': [
            {'key': k, 'value': v}
            for k, v in node_config.get('metadata', {}).items()
        ]},
    }
    if node_config.get('gpu_type'):
        body['guestAccelerators'] = [{
            'acceleratorType': (f'zones/{zone}/acceleratorTypes/'
                                f'{node_config["gpu_type"]}'),
            'acceleratorCount': int(node_config.get('gpu_count', 1)),
        }]
        body['scheduling'] = {'onHostMaintenance': 'TERMINATE'}
    if node_config.get('use_spot'):
        body.setdefault('scheduling', {}).update({
            'provisioningModel': 'SPOT',
            'instanceTerminationAction': 'DELETE',
        })
    if node_config.get('reservation'):
        # Pin to a specific reservation (twin of the reference's
        # reservation-aware placement, sky/clouds/gcp.py specific_
        # reservations): capacity comes from the named block, never
        # opportunistically from open reservations.
        body['reservationAffinity'] = {
            'consumeReservationType': 'SPECIFIC_RESERVATION',
            'key': 'compute.googleapis.com/reservation-name',
            'values': [node_config['reservation']],
        }
    if node_config.get('service_account'):
        body['serviceAccounts'] = [{
            'email': node_config['service_account'],
            'scopes': ['https://www.googleapis.com/auth/cloud-platform'],
        }]
    return body


# ---- MIG / DWS flex-start (twin of sky/provision/gcp/mig_utils.py) ---------


def mig_name(cluster_name: str) -> str:
    return f'xsky-mig-{cluster_name}'[:63].rstrip('-')


def instance_template_body(node_config: Dict[str, Any],
                           cluster_name: str,
                           zone: str) -> Dict[str, Any]:
    """Instance template wrapping vm_body's properties: the MIG stamps
    cluster-labeled VMs from it, so list_cluster/get_cluster_info find
    DWS-provisioned instances exactly like directly-inserted ones."""
    props = vm_body(node_config, cluster_name,
                    vm_name='unused', zone=zone, is_head=True,
                    node_index=0)
    props.pop('name')
    # Templates take bare machine-type names, not zonal URLs; labels
    # drop the per-node identity (the MIG names instances itself —
    # host identity comes from instance enumeration order).
    props['machineType'] = props['machineType'].rsplit('/', 1)[-1]
    for label in (HEAD_LABEL, 'xsky-node-index'):
        props['labels'].pop(label, None)
    return {
        'name': mig_name(cluster_name),
        'properties': props,
    }


def mig_body(cluster_name: str, project: str,
             template_name: str) -> Dict[str, Any]:
    return {
        'name': mig_name(cluster_name),
        'instanceTemplate': (f'projects/{project}/global/'
                             f'instanceTemplates/{template_name}'),
        'baseInstanceName': cluster_name,
        # DWS requires the MIG itself to start empty; capacity arrives
        # through resize requests.
        'targetSize': 0,
        'instanceLifecyclePolicy': {
            'defaultActionOnFailure': 'DO_NOTHING'},
        'updatePolicy': {'type': 'OPPORTUNISTIC'},
    }


def resize_request_body(cluster_name: str, count: int,
                        run_duration_s: Optional[float] = None
                        ) -> Dict[str, Any]:
    body: Dict[str, Any] = {
        'name': f'{mig_name(cluster_name)}-rr',
        'resizeBy': count,
    }
    if run_duration_s:
        # DWS run duration: the capacity is granted for this window
        # then reclaimed (flex-start contract).
        body['requestedRunDuration'] = {
            'seconds': str(int(run_duration_s))}
    return body


# ---- volumes (network persistent disks) -------------------------------
#
# Twin of sky/provision/gcp/volume_utils.py, redesigned around this
# repo's flow: disks are ensured + attached during run_instances, the
# mkfs-if-blank/mount commands ride ClusterInfo.mount_commands, and
# auto_delete disks are labeled so terminate can find them without any
# local state.

AUTO_DELETE_LABEL = 'xsky-auto-delete'

# resources disk_tier → GCP disk type.
DISK_TIER_TYPES = {
    None: 'pd-balanced',
    'low': 'pd-standard',
    'medium': 'pd-balanced',
    'high': 'pd-ssd',
    'ultra': 'pd-extreme',
    'best': 'pd-ssd',
}


def disk_body(volume: Dict[str, Any], cluster_name: str,
              zone: str) -> Dict[str, Any]:
    labels = {CLUSTER_LABEL: cluster_name}
    if volume.get('auto_delete'):
        labels[AUTO_DELETE_LABEL] = 'true'
    disk_type = DISK_TIER_TYPES.get(volume.get('disk_tier'),
                                    'pd-balanced')
    return {
        'name': volume['name'],
        'sizeGb': str(volume.get('size', 100)),
        'type': f'zones/{zone}/diskTypes/{disk_type}',
        'labels': labels,
    }


def validate_volumes(volumes: List[Dict[str, Any]],
                     num_nodes: int) -> None:
    """Fail BEFORE anything is created: a read_write persistent disk
    attaches to one instance only, so multi-node clusters need
    read_only (multi-attach) volumes."""
    for vol in volumes or []:
        if (vol.get('attach_mode', 'read_write') == 'read_write'
                and num_nodes > 1):
            raise exceptions.InvalidSkyTpuConfigError(
                f'Volume {vol["name"]!r} is read_write but the cluster '
                f'spans {num_nodes} nodes; GCP persistent disks attach '
                'read-write to one instance only. Use attach_mode: '
                'read_only for shared volumes.')


def ensure_disk(gce: 'ComputeClient', vol: Dict[str, Any],
                cluster_name: str, zone: str) -> None:
    """Create the disk if missing; surface spec drift when reusing.

    A read_only volume must already exist: it is unwritable from this
    cluster, so a freshly created blank one could never be formatted
    or populated — creating it here would only produce an unmountable
    device at runtime setup.
    """
    existing = gce.get_disk(vol['name'])
    if existing is None:
        if vol.get('attach_mode') == 'read_only':
            raise exceptions.InvalidSkyTpuConfigError(
                f'read_only volume {vol["name"]!r} does not exist in '
                f'{zone}. Create and populate it first (e.g. a '
                'single-node cluster with attach_mode: read_write).')
        gce.wait_operation(
            gce.insert_disk(disk_body(vol, cluster_name, zone)))
        return
    # Reuse: the request's size/tier/auto_delete do NOT apply to an
    # existing disk — say so instead of silently diverging.
    want_size = str(vol.get('size', 100))
    if existing.get('sizeGb') not in (None, want_size):
        logger.warning(
            f'Volume {vol["name"]!r} exists with sizeGb='
            f'{existing.get("sizeGb")}; requested size {want_size} '
            'is ignored (resize disks via the cloud console/CLI).')
    if (vol.get('auto_delete') and existing.get('labels', {})
            .get(AUTO_DELETE_LABEL) != 'true'):
        logger.warning(
            f'Volume {vol["name"]!r} pre-exists without the '
            f'{AUTO_DELETE_LABEL} label; auto_delete only applies to '
            'disks this provisioner creates — it will NOT be deleted '
            'at teardown.')


def ensure_and_attach_volumes(gce: 'ComputeClient',
                              volumes: List[Dict[str, Any]],
                              cluster_name: str, vm_names: List[str],
                              zone: str) -> None:
    """Create missing disks and attach them to every node."""
    if not volumes:
        return
    validate_volumes(volumes, len(vm_names))
    for vol in volumes:
        ensure_disk(gce, vol, cluster_name, zone)
    for vm_name in vm_names:
        attached = {d.get('deviceName')
                    for d in gce.get(vm_name).get('disks', [])}
        for vol in volumes:
            if vol['name'] in attached:
                continue
            mode = ('READ_ONLY' if vol.get('attach_mode') == 'read_only'
                    else 'READ_WRITE')
            gce.wait_operation(gce.attach_disk(vm_name, {
                'source': (f'projects/{gce.project}/zones/{zone}/disks/'
                           f'{vol["name"]}'),
                'deviceName': vol['name'],
                'mode': mode,
            }))


def volume_mount_commands(volumes: List[Dict[str, Any]],
                          tpu: bool = False) -> List[str]:
    """Idempotent mkfs-if-blank + mount for each attached volume.

    Compute VMs expose an attached disk with deviceName NAME at
    /dev/disk/by-id/google-NAME; TPU VMs name dataDisks
    persistent-disk-{i+1} in attach order. ext4 is only created when
    the device has no filesystem (blkid rc!=0), so data survives
    re-attachment. Steps chain with && so ANY failure (missing device,
    bad filesystem, mount error) exits non-zero and fails the launch —
    a silently-unmounted "persistent" path writing to the boot disk is
    the worst outcome.
    """
    import shlex
    cmds = []
    for i, vol in enumerate(volumes or []):
        device = (f'persistent-disk-{i + 1}' if tpu else vol['name'])
        dev = shlex.quote(f'/dev/disk/by-id/google-{device}')
        path = shlex.quote(vol['path'])
        read_only = vol.get('attach_mode') == 'read_only'
        steps = []
        if not read_only:
            steps.append(f'(sudo blkid {dev} >/dev/null 2>&1 || '
                         f'sudo mkfs.ext4 -q {dev})')
        steps.append(f'sudo mkdir -p {path}')
        opts = '-o ro ' if read_only else ''
        steps.append(f'(mountpoint -q {path} || '
                     f'sudo mount {opts}{dev} {path})')
        if not read_only:
            steps.append(f'sudo chmod 777 {path}')
        cmds.append(' && '.join(steps))
    return cmds


def delete_auto_delete_volumes(gce: 'ComputeClient',
                               cluster_name: str) -> None:
    """Best-effort delete of this cluster's auto_delete-labeled disks
    (instances must already be gone — GCP refuses to delete attached
    disks, which is the safety net for shared volumes)."""
    label_filter = (f'labels.{CLUSTER_LABEL}={cluster_name} AND '
                    f'labels.{AUTO_DELETE_LABEL}=true')
    for disk in gce.list_disks(label_filter):
        try:
            gce.wait_operation(gce.delete_disk(disk['name']))
        except rest.GcpApiError as e:
            logger.warning(
                f'auto_delete volume {disk["name"]!r} not deleted: {e}')


def vm_instance_info(inst: Dict[str, Any]) -> Dict[str, Any]:
    nic = (inst.get('networkInterfaces') or [{}])[0]
    access = (nic.get('accessConfigs') or [{}])[0]
    return {
        'instance_id': inst['name'],
        'internal_ip': nic.get('networkIP', ''),
        'external_ip': access.get('natIP'),
        'status': inst.get('status', 'UNKNOWN'),
        'tags': dict(inst.get('labels', {})),
        'slice_id': None,
        'host_index': 0,
    }
