"""Name → class registries (twin of reference sky/utils/registry.py:129).

Used for clouds, backends and managed-job recovery strategies so components
self-register at import time and are looked up by canonical lowercase name.
"""
from __future__ import annotations

from typing import Callable, Dict, Generic, List, Optional, Type, TypeVar

T = TypeVar('T')


class Registry(Generic[T]):

    def __init__(self, registry_name: str) -> None:
        self._name = registry_name
        self._registry: Dict[str, T] = {}
        self._aliases: Dict[str, str] = {}
        self._default: Optional[str] = None

    def register(self,
                 name: Optional[str] = None,
                 aliases: Optional[List[str]] = None,
                 default: bool = False) -> Callable[[Type], Type]:

        def decorator(cls: Type) -> Type:
            key = (name or cls.__name__).lower()
            if key in self._registry:
                raise ValueError(
                    f'{self._name}: duplicate registration for {key!r}')
            # Clouds register an instance; everything else the class itself.
            self._registry[key] = cls() if getattr(cls, '_REGISTER_INSTANCE',
                                                   False) else cls
            for alias in aliases or []:
                self._aliases[alias.lower()] = key
            if default:
                self._default = key
            return cls

        return decorator

    def from_str(self, name: Optional[str]) -> Optional[T]:
        if name is None:
            return None
        key = name.lower()
        key = self._aliases.get(key, key)
        if key not in self._registry:
            valid = ', '.join(sorted(self._registry))
            raise ValueError(
                f'{self._name} {name!r} not found. Valid: {valid}.')
        return self._registry[key]

    def get_default(self) -> Optional[T]:
        if self._default is None:
            return None
        return self._registry[self._default]

    def keys(self) -> List[str]:
        return sorted(self._registry)

    def values(self) -> List[T]:
        return [self._registry[k] for k in sorted(self._registry)]

    def __contains__(self, name: str) -> bool:
        key = name.lower()
        return self._aliases.get(key, key) in self._registry


# Populated by skypilot_tpu.clouds / backends / jobs.recovery at import time.
CLOUD_REGISTRY: Registry = Registry('cloud')
BACKEND_REGISTRY: Registry = Registry('backend')
JOBS_RECOVERY_STRATEGY_REGISTRY: Registry = Registry('recovery strategy')
