"""Chaos-layer tests: plan mechanics, the zero-overhead-when-disabled
guarantee, the tier-1 preemption-storm smoke (docs/robustness.md's
worked example), and thin wrappers over the tools/xskylint rules that
used to live here as ad-hoc AST lints (see docs/static-analysis.md)."""
import json
import os
import sys
import time

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.utils import chaos


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.clear()
    yield
    chaos.clear()


class TestChaosPlan:

    def test_disabled_is_zero_overhead(self):
        assert 'XSKY_CHAOS_PLAN' not in os.environ
        assert not chaos.enabled()
        assert chaos.inject('jobs.status_probe', job_id=1) is None
        # The acceptance-criteria assertion: with no plan loaded the
        # instrumented hot paths leave no trace — not even hit counts.
        assert chaos.counters() == {}
        assert chaos.fired() == {}

    def test_first_n_and_skip_first(self):
        chaos.load_plan({'points': {
            'p': {'skip_first': 1, 'first_n': 2}}})
        fires = [chaos.inject('p') is not None for _ in range(5)]
        assert fires == [False, True, True, False, False]
        assert chaos.hits('p') == 5
        assert chaos.fired()['p'] == 2

    def test_every_kth(self):
        chaos.load_plan({'points': {'p': {'every_kth': 3}}})
        fires = [chaos.inject('p') is not None for _ in range(7)]
        assert fires == [False, False, True, False, False, True, False]

    def test_match_selector_filters_on_context(self):
        chaos.load_plan({'points': {
            'gang.host_start': {'match': {'rank': 1}, 'first_n': 1}}})
        assert chaos.inject('gang.host_start', rank=0) is None
        # Non-matching hits don't consume the rule's first_n budget.
        assert chaos.inject('gang.host_start', rank=1) is not None
        assert chaos.inject('gang.host_start', rank=1) is None
        assert chaos.hits('gang.host_start') == 3

    def test_seeded_probability_is_deterministic(self):
        def run():
            chaos.load_plan({'seed': 11, 'points': {
                'p': {'probability': 0.5}}})
            return [chaos.inject('p') is not None for _ in range(20)]

        first, second = run(), run()
        assert first == second
        assert any(first) and not all(first)

    def test_rule_list_first_match_wins(self):
        chaos.load_plan({'points': {'p': [
            {'first_n': 1, 'returncode': 255},
            {'skip_first': 1, 'first_n': 1, 'error': 'RuntimeError'},
        ]}})
        assert chaos.inject('p')['returncode'] == 255
        with pytest.raises(RuntimeError):
            chaos.inject('p')
        assert chaos.inject('p') is None

    def test_error_resolution_prefers_xsky_exceptions(self):
        chaos.load_plan({'points': {
            'a': {'error': 'CapacityError'},
            'b': {'error': 'TimeoutError'},
            'c': {'error': 'NoSuchErrorType'}}})
        with pytest.raises(exceptions.CapacityError):
            chaos.inject('a')
        with pytest.raises(TimeoutError):
            chaos.inject('b')
        with pytest.raises(chaos.ChaosError):
            chaos.inject('c')

    def test_signal_action_delivers_to_self(self):
        """The `signal` action (crash drills: SIGKILL a controller
        mid-flight) sends the configured signal to the injecting
        process — verified with a catchable signal."""
        import signal as signal_lib
        received = []
        old = signal_lib.signal(signal_lib.SIGUSR1,
                                lambda *a: received.append(1))
        try:
            chaos.load_plan({'points': {
                'p': {'first_n': 1, 'signal': 'SIGUSR1'}}})
            chaos.inject('p')
            assert received == [1]
            assert chaos.inject('p') is None   # rule spent
        finally:
            signal_lib.signal(signal_lib.SIGUSR1, old)

    def test_unknown_signal_name_raises_chaos_error(self):
        chaos.load_plan({'points': {'p': {'signal': 'SIGNOPE'}}})
        with pytest.raises(chaos.ChaosError):
            chaos.inject('p')

    def test_latency_action_sleeps(self):
        chaos.load_plan({'points': {'p': {'latency_s': 0.05}}})
        start = time.monotonic()
        assert chaos.inject('p') is not None
        assert time.monotonic() - start >= 0.05

    def test_latency_action_journals_measured_duration(
            self, fake_cluster_env):
        """The journal row records the MEASURED sleep, not the plan's
        configured value (an oversleeping host is the signal), and the
        fire lands on the active trace span with that latency."""
        del fake_cluster_env
        from skypilot_tpu import state as state_lib
        from skypilot_tpu.utils import tracing
        chaos.load_plan({'points': {'p': {'latency_s': 0.05}}})
        with tracing.span('chaos.host') as sp:
            chaos.inject('p')
        rows = state_lib.get_recovery_events(
            event_type='chaos.injected')
        assert len(rows) == 1
        measured = rows[0]['latency_s']
        assert measured is not None and measured >= 0.05
        # Measured, not configured: a real sleep always overshoots.
        assert measured != 0.05
        span_row = state_lib.get_spans(sp.trace_id)[0]
        fires = span_row['attrs']['chaos_fires']
        assert fires[0]['point'] == 'p'
        assert fires[0]['latency_s'] >= 0.05
        # Journal row cross-links to the span's trace.
        assert rows[0]['trace_id'] == sp.trace_id

    def test_plan_from_env_json_and_file(self, monkeypatch, tmp_path):
        monkeypatch.setenv('XSKY_CHAOS_PLAN',
                           '{"points": {"p": {"first_n": 1}}}')
        assert chaos.enabled()
        assert chaos.inject('p') is not None
        plan_file = tmp_path / 'plan.json'
        plan_file.write_text(json.dumps(
            {'points': {'q': {'first_n': 1}}}))
        monkeypatch.setenv('XSKY_CHAOS_PLAN', str(plan_file))
        # New env value → fresh plan (counters reset with it).
        assert chaos.inject('q') is not None
        assert chaos.hits('p') == 0
        monkeypatch.delenv('XSKY_CHAOS_PLAN')
        assert not chaos.enabled()
        assert chaos.counters() == {}

    def test_invalid_plan_disables_chaos_not_recovery(
            self, monkeypatch, tmp_path):
        """A typo'd plan must never crash the instrumented recovery
        paths: it is logged and ignored (and the empty counters make a
        test driving a broken plan fail loudly on its hit asserts)."""
        monkeypatch.setenv('XSKY_CHAOS_PLAN', '{not json')
        assert chaos.inject('p') is None
        assert not chaos.enabled()
        assert chaos.counters() == {}
        monkeypatch.setenv('XSKY_CHAOS_PLAN',
                           str(tmp_path / 'missing.json'))
        assert chaos.inject('p') is None
        # A corrected plan takes effect without a restart.
        monkeypatch.setenv('XSKY_CHAOS_PLAN',
                           '{"points": {"p": {"first_n": 1}}}')
        assert chaos.inject('p') is not None

    def test_fire_journals_recovery_event(self, fake_cluster_env):
        del fake_cluster_env
        from skypilot_tpu import state as state_lib
        chaos.load_plan({'points': {
            'runner.run': {'first_n': 1, 'latency_s': 0.0}}})
        chaos.inject('runner.run', node='h0')
        rows = state_lib.get_recovery_events(
            event_type='chaos.injected')
        assert len(rows) == 1
        assert rows[0]['scope'] == 'chaos/runner.run'
        assert rows[0]['detail'] == {'node': 'h0'}


class TestInstrumentedHotPaths:
    """The chaos points actually sit on the paths they claim to."""

    def test_command_runner_subclasses_are_instrumented(self, tmp_path):
        from skypilot_tpu.utils import command_runner as runner_lib
        chaos.load_plan({'points': {
            'runner.run': {'first_n': 1, 'error': 'ConnectionError'}}})
        runner = runner_lib.LocalProcessCommandRunner(
            'h0', host_root=str(tmp_path / 'h0'))
        with pytest.raises(ConnectionError):
            runner.run('true')
        assert runner.run('true') == 0   # second run: rule spent
        assert chaos.hits('runner.run') == 2

    def test_serve_probe_tolerates_one_injected_drop(
            self, monkeypatch, tmp_path):
        """A single dropped readiness request must not flap the replica
        to NOT_READY: the probe's retry_transient absorbs it."""
        import http.server
        import threading

        from skypilot_tpu.serve import replica_managers
        from skypilot_tpu.serve import service_spec as spec_lib
        from skypilot_tpu.serve import state as serve_state

        monkeypatch.setenv('XSKY_SERVE_DB', str(tmp_path / 'serve.db'))

        class _OK(http.server.BaseHTTPRequestHandler):

            def do_GET(self):
                self.send_response(200)
                self.end_headers()

            def log_message(self, *args):
                pass

        server = http.server.HTTPServer(('127.0.0.1', 0), _OK)
        threading.Thread(target=server.serve_forever,
                         daemon=True).start()
        try:
            serve_state.add_service('flap', {}, 0)
            mgr = replica_managers.ReplicaManager(
                'flap', {}, spec_lib.SkyServiceSpec(readiness_path='/'))
            chaos.load_plan({'points': {
                'serve.probe': {'first_n': 1,
                                'error': 'ConnectionError'}}})
            endpoint = '127.0.0.1:%d' % server.server_address[1]
            assert mgr._probe(endpoint) is True
            assert chaos.hits('serve.probe') == 2
            # A persistent fault (every attempt) does fail the probe.
            chaos.load_plan({'points': {
                'serve.probe': {'error': 'ConnectionError'}}})
            assert mgr._probe(endpoint) is False
        finally:
            server.shutdown()

    def test_disabled_instrumented_paths_leave_no_trace(self, tmp_path):
        """End-to-end form of the zero-overhead guarantee: drive real
        instrumented code (runner + gang fan-out) with no plan loaded
        and assert the chaos layer recorded nothing."""
        from skypilot_tpu.agent import gang
        from skypilot_tpu.utils import command_runner as runner_lib
        runner = runner_lib.LocalProcessCommandRunner(
            'h0', host_root=str(tmp_path / 'h0'))
        runner.run('true')
        result = gang.gang_launch([runner], [{}], 'echo quiet',
                                  str(tmp_path / 'logs'),
                                  poll_interval_s=0.05)
        assert result.success
        assert chaos.counters() == {}


# ---- migrated AST lints ----------------------------------------------------
# The AST lints that accumulated here across PRs 1-7 (raw-sleep,
# sequential runner loops, lease heartbeats, telemetry-blind polls,
# retention bounds, span coverage x3, SELECT paging) now run through
# tools/xskylint: ONE parse per file, every rule over the shared AST,
# uniform `# xskylint: disable=<rule> -- <reason>` suppressions.
# Legacy exemption comments (`# full-scan ok:` ...) keep working via
# the engine's LEGACY_MARKERS compatibility map. The classes below
# keep the historical lint names discoverable where they lived and
# prove coverage is unchanged: each runs its rule over the real tree
# through the shared engine and re-asserts the rule still catches a
# synthetic violation. Per-rule positive/negative fixtures and the
# engine mechanics live in test_xskylint.py.

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), '..', '..'))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


def _write_tree(root, files):
    for rel, source in files.items():
        path = os.path.join(str(root), rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, 'w', encoding='utf-8') as f:
            f.write(source)


def _lint_repo_clean(rule_id):
    from tools.xskylint import engine as lint_engine
    result = lint_engine.lint_paths(REPO_ROOT,
                                    ['skypilot_tpu', 'tools'],
                                    rule_ids=[rule_id])
    assert not result.unsuppressed, (
        f'[{rule_id}] violations in the tree:\n  ' +
        '\n  '.join(f.render() for f in result.unsuppressed))


def _lint_sources(rule_id, files, tmp_path):
    from tools.xskylint import engine as lint_engine
    _write_tree(tmp_path, files)
    result = lint_engine.lint_paths(str(tmp_path), ['.'],
                                    rule_ids=[rule_id])
    return result.unsuppressed


class TestNoRawSleepLint:
    """Thin wrapper over the engine's `no-raw-sleep` rule (legacy
    home of the lint; rationale in docs/static-analysis.md)."""

    def test_instrumented_modules_use_resilience_helpers(self):
        _lint_repo_clean('no-raw-sleep')

    def test_lint_catches_a_raw_sleep(self, tmp_path):
        bad = {'skypilot_tpu/jobs/controller.py':
               'import time\n'
               'def poll():\n'
               '    while True:\n'
               '        time.sleep(1)\n'}
        assert _lint_sources('no-raw-sleep', bad, tmp_path)


class TestNoSequentialRunnerLoopLint:
    """Thin wrapper over `no-sequential-runner-loop`."""

    def test_no_sequential_runner_loops_in_control_plane(self):
        _lint_repo_clean('no-sequential-runner-loop')

    def test_lint_catches_a_sequential_runner_loop(self, tmp_path):
        bad = {'skypilot_tpu/serve/sync.py':
               'def setup(runners):\n'
               '    for rank, runner in enumerate(runners):\n'
               '        runner.run("true")\n'}
        assert _lint_sources('no-sequential-runner-loop', bad,
                             tmp_path)


class TestLeaseHeartbeatLint:
    """Thin wrapper over `lease-heartbeat`."""

    def test_lease_holding_loops_heartbeat(self):
        _lint_repo_clean('lease-heartbeat')

    def test_lint_catches_a_heartbeatless_loop(self, tmp_path):
        bad = {'skypilot_tpu/serve/controller.py':
               'def run(self):\n'
               '    while True:\n'
               '        self.tick()\n'}
        assert _lint_sources('lease-heartbeat', bad, tmp_path)


class TestTelemetryStalenessLint:
    """Thin wrapper over `telemetry-poll`."""

    def test_rank_state_poll_loops_consult_telemetry(self):
        _lint_repo_clean('telemetry-poll')

    def test_lint_catches_a_telemetry_blind_loop(self, tmp_path):
        bad = {'skypilot_tpu/jobs/controller.py':
               'def _run_task(self):\n'
               '    while True:\n'
               '        self._job_status()\n'}
        assert _lint_sources('telemetry-poll', bad, tmp_path)


class TestTelemetryRetentionLint:
    """Thin wrapper over `retention-bound`."""

    def test_state_observability_tables_are_bounded(self):
        _lint_repo_clean('retention-bound')

    def test_lint_catches_an_unbounded_table(self, tmp_path):
        bad = {'skypilot_tpu/state.py':
               'C = """CREATE TABLE IF NOT EXISTS foo_telemetry '
               '(x INT);"""\n'}
        assert _lint_sources('retention-bound', bad, tmp_path)


class TestSpanCoverageLint:
    """Thin wrapper over `span-fanout` + `span-failover`."""

    def test_every_fanout_call_site_runs_under_a_span(self):
        _lint_repo_clean('span-fanout')

    def test_failover_retry_loops_run_under_a_span(self):
        _lint_repo_clean('span-failover')

    def test_lint_catches_an_uncovered_fanout_call(self, tmp_path):
        # A span enclosing only the DEFINITION of a nested function
        # does not cover calls inside it.
        leaky = {'skypilot_tpu/backends/fan.py':
                 'def outer():\n'
                 '    with tracing.span("outer"):\n'
                 '        def inner():\n'
                 '            parallelism.run_in_parallel(f, [])\n'
                 '        inner()\n'}
        findings = _lint_sources('span-fanout', leaky, tmp_path)
        assert [f for f in findings if f.line == 4]


class TestProfilerSpanLint:
    """Thin wrapper over `span-profiler`."""

    def test_every_profiler_site_runs_under_a_span(self):
        _lint_repo_clean('span-profiler')

    def test_lint_catches_an_uncovered_profiler_site(self, tmp_path):
        bad = {'skypilot_tpu/core.py':
               'def cap(backend, handle):\n'
               '    backend.capture_device_profile(handle)\n'}
        assert _lint_sources('span-profiler', bad, tmp_path)


class TestListingLimitLint:
    """Thin wrapper over `select-limit`."""

    def test_state_listing_functions_are_paged_or_exempt(self):
        _lint_repo_clean('select-limit')

    def test_lint_catches_an_unpaged_listing(self, tmp_path):
        bad = {'skypilot_tpu/state.py':
               'def list_things():\n'
               "    return _read('SELECT x FROM t')\n"}
        assert _lint_sources('select-limit', bad, tmp_path)

    def test_full_scan_exemption_comment_still_works(self, tmp_path):
        """The legacy `# full-scan ok:` comments written before the
        engine existed keep suppressing (compatibility map)."""
        exempt = {'skypilot_tpu/state.py':
                  'def list_things():\n'
                  '    # full-scan ok: one row per enabled cloud.\n'
                  "    return _read('SELECT x FROM t')\n"}
        assert _lint_sources('select-limit', exempt, tmp_path) == []


class TestChaosSmoke:
    """The acceptance scenario, deterministic and hermetic (tier-1):
    a seeded plan injects (a) an rc-255 SSH drop on a gang host during
    fan-out, (b) a hung status probe, and (c) one mid-run preemption —
    the managed job must recover end-to-end and the journal must hold
    the full fault→recovery timeline."""

    STORM_PLAN = {
        'seed': 7,
        'points': {
            # (a) First host start of the run fan-out dies like a
            # dropped SSH transport; the gang launcher retries it.
            'gang.host_start': {'first_n': 1, 'returncode': 255},
            # (b) The third status probe hangs briefly, then errors.
            'jobs.status_probe': {'skip_first': 2, 'first_n': 1,
                                  'latency_s': 0.05,
                                  'error': 'TimeoutError'},
            # (c) The probe failure makes the controller consult cloud
            # truth — the first such query preempts the cluster
            # out-of-band (the fake cloud acting as a chaotic provider).
            'fake.preempt': {'first_n': 1},
        },
    }

    def test_preemption_storm_recovers_end_to_end(
            self, fake_cluster_env, monkeypatch, tmp_path):
        del fake_cluster_env
        from skypilot_tpu import Resources, Task
        from skypilot_tpu import state as state_lib
        from skypilot_tpu.jobs import controller as controller_lib
        from skypilot_tpu.jobs import scheduler as jobs_scheduler
        from skypilot_tpu.jobs import state as jobs_state

        monkeypatch.setenv('XSKY_JOBS_DB',
                           str(tmp_path / 'managed_jobs.db'))
        monkeypatch.setenv('XSKY_JOBS_LOG_DIR', str(tmp_path / 'jlogs'))
        # The env var is read at module import, which may predate this
        # test — pin the attribute so the third probe lands while the
        # sleep-1 task is still running.
        monkeypatch.setattr(controller_lib, 'POLL_INTERVAL_S', 0.2)
        plan_file = tmp_path / 'storm.json'
        plan_file.write_text(json.dumps(self.STORM_PLAN))
        # Via the env var (not load_plan) so the whole process tree —
        # the job_runner on the fake head host included — sees the plan.
        monkeypatch.setenv('XSKY_CHAOS_PLAN', str(plan_file))

        # Long enough that the third probe (the injected failure) always
        # lands while the task is still mid-run, even on a loaded box.
        task = Task('storm', run='sleep 3; echo storm-ok')
        task.set_resources(Resources(accelerators='tpu-v5e-8',
                                     use_spot=True))
        job_id = jobs_state.add_job('storm', Task.chain_to_config([task]))
        jobs_state.set_status(job_id,
                              jobs_state.ManagedJobStatus.SUBMITTED)
        # Run the controller in-process (the scheduler would exec it as
        # a subprocess): deterministic, and the controller-side chaos
        # hit counters stay visible to the test.
        jobs_state.set_schedule_state(job_id,
                                      jobs_state.ScheduleState.LAUNCHING)
        # Claim the controller slot for THIS process, or the scheduler's
        # dead-controller reconciler (pid None ≙ dead) would re-exec a
        # competing subprocess controller mid-test.
        jobs_state.set_controller_pid(job_id, os.getpid())
        try:
            controller_lib.JobsController(job_id).run()
        finally:
            jobs_scheduler.job_done(job_id)

        record = jobs_state.get_job(job_id)
        assert record['status'] == \
            jobs_state.ManagedJobStatus.SUCCEEDED, record
        assert record['recovery_count'] >= 1

        # Every injected fault is journalled with its point as scope...
        injected = {r['scope'] for r in state_lib.get_recovery_events(
            event_type='chaos.injected')}
        assert 'chaos/jobs.status_probe' in injected
        assert 'chaos/fake.preempt' in injected
        # (the gang.host_start row is written by the job_runner process
        # on the fake head host — cross-process via the shared state DB)
        assert 'chaos/gang.host_start' in injected

        # ...and the preemption→recovery story is one readable timeline
        # with a measured recovery latency.
        job_events = state_lib.get_recovery_events(scope=f'job/{job_id}')
        types = [r['event_type'] for r in job_events]
        assert 'job.preempted' in types
        assert 'job.recovered' in types
        recovered = job_events[types.index('job.recovered')]
        assert recovered['latency_s'] is not None
        assert recovered['latency_s'] > 0
        assert job_events[types.index('job.preempted')]['cause']

        # Controller-side points were traversed in this process.
        assert chaos.hits('jobs.status_probe') >= 3
        assert chaos.hits('fake.preempt') >= 1
