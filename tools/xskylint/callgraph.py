"""Pass 3 of the whole-program analyzer: the call graph.

Built from the SAME shared per-file ASTs the engine already parses
(one ``ast.parse`` per file — the parse-once counter test covers all
three passes). :func:`harvest_into` runs during ``ProjectIndex.add_file``
and records one :class:`FunctionNode` per module-level function and per
method of a top-level class; :class:`CallGraph` resolves their call
sites into edges lazily when an interprocedural rule asks.

**Resolution (bounded best-effort).** A call site resolves when it is:

  * a bare name bound to a same-module function or class
    (``_flush()``, ``_Emitter(path)`` → ``_Emitter.__init__``), or a
    name imported with ``from mod import fn``;
  * ``self.method()`` / ``cls.method()`` → the same class's method;
  * ``alias.attr()`` where ``alias`` is an imported module (module- or
    function-level import) → that module's function or class;
  * ``obj.method()`` where ``method`` names a method of exactly ONE
    class in the same module (the local-instance pattern:
    ``emitter.update`` → ``_Emitter.update``). This deliberately
    over-approximates — over-approximation is the safe direction for
    purity/lock analyses;
  * ``run_in_parallel(fn, ...)`` and ``Thread(target=fn)`` indirection
    (thread targets are tagged ``spawn`` — the work runs on ANOTHER
    thread, so hot-path and held-lock propagation skip those edges).

Anything else (attribute chains like ``self.engine.decode_step``,
calls through locals the heuristics can't type) is an **unknown edge**,
counted per node and surfaced by ``xsky lint --why`` and the call-graph
tests — the soundness limit is explicit, not silent.

**Per-node facts** harvested alongside the edges:

  * blocking-primitive call sites (sleep, DB, network, subprocess,
    non-spool filesystem writes, fan-out, ``.wait()``) with the set of
    module-level locks lexically held and the ``# hotpath ok: <bound>``
    exemption state (marker on the site line, the comment block above
    it, or the enclosing ``def``);
  * module-lock acquisitions (``with <lock>:``) with the locks already
    held — the lock-order graph's raw edges;
  * never-raise facts: the first statement that could raise outside a
    broad ``try`` (``raise``/``assert``/subscripts/attribute loads) and
    every call made from an ``except``/``else``/``finally`` arm that
    escapes the guard — the transitive never-raise rule's inputs.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Any, Dict, List, Optional, Set, Tuple

HOTPATH_MARKER = '# hotpath ok'

# Receivers recognized as the requests-style HTTP client modules.
_NETWORK_RECVS = frozenset({'requests', 'httplib', 'httpx'})
# os functions that write/mutate the filesystem.
_OS_FS_WRITE = frozenset({
    'replace', 'rename', 'renames', 'makedirs', 'mkdir', 'remove',
    'unlink', 'rmdir', 'fsync', 'truncate', 'symlink', 'link'})
_FILE_WRITE_ATTRS = frozenset({'write_text', 'write_bytes'})
# open() modes that write.
_WRITE_MODE_CHARS = ('w', 'a', 'x', '+')


@dataclasses.dataclass
class CallSite:
    """One call expression, with enough shape to resolve it later."""
    lineno: int
    kind: str                  # 'name' | 'self' | 'recv' | 'dynamic'
    name: str                  # called function/method name
    recv: str = ''             # receiver name for kind='recv'
    held: Tuple[str, ...] = ()         # module locks lexically held
    protected: bool = False    # inside a broad-try body (guarded)
    in_arm: bool = False       # in an except/else/finally arm that
                               # escapes the enclosing guard
    spawn: bool = False        # thread-target indirection: runs on
                               # another thread, not this call path


@dataclasses.dataclass
class PrimitiveSite:
    """One blocking-primitive call site."""
    lineno: int
    kind: str                  # 'sleep'|'db'|'network'|'subprocess'|
                               # 'fs-write'|'fanout'|'wait'
    desc: str                  # e.g. 'time.sleep', 'urlopen'
    held: Tuple[str, ...] = ()
    exempt: bool = False       # `# hotpath ok: <bound>` covers it


@dataclasses.dataclass
class LockAcq:
    """One ``with <module lock>:`` acquisition."""
    lineno: int
    lock: str                  # qualified '<rel_path>::<name>'
    held: Tuple[str, ...] = () # locks already held at this point
    exempt: bool = False


@dataclasses.dataclass
class FunctionNode:
    rel_path: str
    qual: str                  # 'Trainer.step' or 'emit'
    lineno: int
    cls: Optional[str]
    calls: List[CallSite] = dataclasses.field(default_factory=list)
    primitives: List[PrimitiveSite] = dataclasses.field(
        default_factory=list)
    lock_acqs: List[LockAcq] = dataclasses.field(default_factory=list)
    imports: Dict[str, str] = dataclasses.field(default_factory=dict)
    # First construct that could raise outside broad-try protection
    # (None ⇒ lexically no-raise, modulo its calls).
    risky_line: Optional[int] = None
    risky_what: str = ''
    exempt_all: bool = False   # marker on the def line / block above

    @property
    def name(self) -> str:
        return self.qual.rsplit('.', 1)[-1]

    def handler_calls(self) -> List[CallSite]:
        """Calls in except/else/finally arms that escape the guard."""
        return [c for c in self.calls if c.in_arm and not c.protected]

    def unprotected_calls(self) -> List[CallSite]:
        return [c for c in self.calls if not c.protected]

    def _note_risky(self, lineno: int, what: str) -> None:
        if self.risky_line is None:
            self.risky_line, self.risky_what = lineno, what


def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
    return handler.type is None or (
        isinstance(handler.type, ast.Name) and
        handler.type.id in ('Exception', 'BaseException'))


def _try_protects(node: ast.Try) -> bool:
    """A try protects its body when some handler catches broadly and
    no handler re-raises."""
    if not any(_is_broad_handler(h) for h in node.handlers):
        return False
    for handler in node.handlers:
        for sub in ast.walk(handler):
            if isinstance(sub, ast.Raise):
                return False
    return True


def _marker_covers(lines: List[str], lineno: int) -> bool:
    """``# hotpath ok:`` on `lineno` or the contiguous comment block
    immediately above it."""
    if 1 <= lineno <= len(lines) and HOTPATH_MARKER in lines[lineno - 1]:
        return True
    i = lineno - 1
    while 1 <= i <= len(lines) and lines[i - 1].strip().startswith('#'):
        if HOTPATH_MARKER in lines[i - 1]:
            return True
        i -= 1
    return False


def _harvest_imports(nodes, out: Dict[str, str]) -> None:
    for node in nodes:
        if isinstance(node, ast.Import):
            for alias in node.names:
                bound = alias.asname or alias.name.split('.')[0]
                out[bound] = alias.name if alias.asname else \
                    alias.name.split('.')[0]
        elif isinstance(node, ast.ImportFrom) and node.module and \
                node.level == 0:
            for alias in node.names:
                out[alias.asname or alias.name] = \
                    f'{node.module}.{alias.name}'


class _FunctionHarvester:
    """Walks ONE function body, folding nested defs in (a closure
    passed to run_in_parallel / retry_transient belongs to its parent's
    call path, best-effort) and tracking lexical state: held module
    locks, broad-try protection, guard-escaping arms."""

    def __init__(self, node: FunctionNode, module_locks: Set[str],
                 lines: List[str]) -> None:
        self.node = node
        self.module_locks = module_locks
        self.lines = lines

    # -- lexical helpers -----------------------------------------------------

    def _lock_of(self, expr: ast.expr) -> Optional[str]:
        if isinstance(expr, ast.Name) and expr.id in self.module_locks:
            return f'{self.node.rel_path}::{expr.id}'
        return None

    # -- the walk ------------------------------------------------------------

    def walk_body(self, body: List[ast.stmt], held: Tuple[str, ...],
                  protected: bool, in_arm: bool) -> None:
        for stmt in body:
            self._stmt(stmt, held, protected, in_arm)

    def _stmt(self, stmt: ast.stmt, held: Tuple[str, ...],
              protected: bool, in_arm: bool) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Nested def: body runs when CALLED — fold its facts into
            # the parent but reset the lexical state (locks/guards do
            # not span the call boundary).
            _harvest_imports(ast.walk(stmt), self.node.imports)
            self.walk_body(stmt.body, (), False, False)
            return
        if isinstance(stmt, ast.ClassDef):
            return   # nested classes: out of the bounded scope
        if isinstance(stmt, (ast.Import, ast.ImportFrom)):
            _harvest_imports([stmt], self.node.imports)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            acquired = list(held)
            for item in stmt.items:
                self._exprs(item.context_expr, tuple(acquired),
                            protected, in_arm)
                lock = self._lock_of(item.context_expr)
                if lock is not None:
                    self.node.lock_acqs.append(LockAcq(
                        lineno=stmt.lineno, lock=lock,
                        held=tuple(acquired),
                        exempt=_marker_covers(self.lines, stmt.lineno)
                        or self.node.exempt_all))
                    acquired.append(lock)
            self.walk_body(stmt.body, tuple(acquired), protected,
                           in_arm)
            return
        if isinstance(stmt, ast.Try):
            protects = _try_protects(stmt)
            self.walk_body(stmt.body, held, protected or protects,
                           in_arm)
            for handler in stmt.handlers:
                # Handler arms escape THIS guard: exceptions raised
                # here propagate to the caller.
                self.walk_body(handler.body, held, False, True)
            self.walk_body(stmt.orelse, held, False, True)
            self.walk_body(stmt.finalbody, held, False, True)
            return
        if isinstance(stmt, ast.Raise):
            if not protected:
                self.node._note_risky(stmt.lineno, 'raise')
            # A raise's exception expression may carry calls.
            for child in ast.iter_child_nodes(stmt):
                self._exprs(child, held, protected, in_arm)
            return
        if isinstance(stmt, ast.Assert):
            if not protected:
                self.node._note_risky(stmt.lineno, 'assert')
            self._exprs(stmt.test, held, protected, in_arm)
            if stmt.msg is not None:
                self._exprs(stmt.msg, held, protected, in_arm)
            return
        if isinstance(stmt, ast.Match):
            # match arms share the lexical state; case bodies are
            # lists of match_case (not stmt), so the generic fallback
            # below would skip them SILENTLY — handle explicitly.
            self._exprs(stmt.subject, held, protected, in_arm)
            for case in stmt.cases:
                if case.guard is not None:
                    self._exprs(case.guard, held, protected, in_arm)
                self.walk_body(case.body, held, protected, in_arm)
            return
        # Generic statements: scan expressions, recurse into nested
        # statement lists (if/for/while bodies share the lexical
        # state; a loop does not change guard or lock scope).
        for field in ('test', 'iter', 'value', 'targets', 'target'):
            sub = getattr(stmt, field, None)
            if sub is None:
                continue
            for expr in (sub if isinstance(sub, list) else [sub]):
                if isinstance(expr, ast.expr):
                    self._exprs(expr, held, protected, in_arm)
        for field in ('body', 'orelse', 'finalbody'):
            sub = getattr(stmt, field, None)
            if isinstance(sub, list) and sub and \
                    isinstance(sub[0], ast.stmt):
                self.walk_body(sub, held, protected, in_arm)

    # -- expressions ---------------------------------------------------------

    def _exprs(self, expr: ast.expr, held: Tuple[str, ...],
               protected: bool, in_arm: bool) -> None:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                self._call(sub, held, protected, in_arm)
            elif not protected:
                if isinstance(sub, ast.Subscript):
                    self.node._note_risky(sub.lineno, 'subscript')
                elif isinstance(sub, ast.Attribute) and \
                        not getattr(sub, '_xsky_is_callee', False):
                    # Attribute loads can raise AttributeError; the
                    # func of a Call is tagged by _call (ast.walk
                    # yields the Call before its children) and the
                    # call itself is handled via resolution instead.
                    self.node._note_risky(sub.lineno, 'attribute')

    def _call(self, call: ast.Call, held: Tuple[str, ...],
              protected: bool, in_arm: bool) -> None:
        func = call.func
        if isinstance(func, ast.Attribute):
            func._xsky_is_callee = True   # not an AttributeError risk
            # (the receiver expression below it stays risk-checked.)
        site = self._site_of(call, held, protected, in_arm)
        if site is not None:
            self.node.calls.append(site)
        prim = self._primitive_of(call)
        if prim is not None:
            kind, desc = prim
            self.node.primitives.append(PrimitiveSite(
                lineno=call.lineno, kind=kind, desc=desc, held=held,
                exempt=_marker_covers(self.lines, call.lineno)
                or self.node.exempt_all))
        self._indirection(call, held, protected, in_arm)

    def _site_of(self, call: ast.Call, held, protected,
                 in_arm) -> Optional[CallSite]:
        func = call.func
        common = dict(lineno=call.lineno, held=held,
                      protected=protected, in_arm=in_arm)
        if isinstance(func, ast.Name):
            return CallSite(kind='name', name=func.id, **common)
        if isinstance(func, ast.Attribute):
            value = func.value
            if isinstance(value, ast.Name):
                if value.id in ('self', 'cls'):
                    return CallSite(kind='self', name=func.attr,
                                    **common)
                return CallSite(kind='recv', name=func.attr,
                                recv=value.id, **common)
            return CallSite(kind='dynamic', name=func.attr, **common)
        return None   # exotic callee (call on a call, subscript...)

    def _indirection(self, call: ast.Call, held, protected,
                     in_arm) -> None:
        """run_in_parallel(fn, ...) and Thread(target=fn) edges."""
        func = call.func
        callee = func.attr if isinstance(func, ast.Attribute) \
            else getattr(func, 'id', '')
        target: Optional[ast.expr] = None
        spawn = False
        if callee == 'run_in_parallel' and call.args:
            target = call.args[0]
        elif callee == 'Thread':
            for kw in call.keywords:
                if kw.arg == 'target':
                    target, spawn = kw.value, True
        if target is None:
            return
        common = dict(lineno=call.lineno, held=held,
                      protected=protected, in_arm=in_arm, spawn=spawn)
        if isinstance(target, ast.Name):
            self.node.calls.append(
                CallSite(kind='name', name=target.id, **common))
        elif isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id in ('self', 'cls'):
            self.node.calls.append(
                CallSite(kind='self', name=target.attr, **common))

    # -- blocking primitives -------------------------------------------------

    def _primitive_of(self, call: ast.Call
                      ) -> Optional[Tuple[str, str]]:
        func = call.func
        if isinstance(func, ast.Name):
            if func.id == 'open' and self._open_writes(call):
                return 'fs-write', 'open(mode=w/a/x/+)'
            if func.id == 'urlopen':
                return 'network', 'urlopen'
            if func.id == 'run_in_parallel':
                return 'fanout', 'run_in_parallel'
            if func.id == 'Popen':
                return 'subprocess', 'Popen'
            return None
        if not isinstance(func, ast.Attribute):
            return None
        attr = func.attr
        recv = func.value.id if isinstance(func.value, ast.Name) else ''
        if attr == 'sleep':
            return 'sleep', f'{recv or "?"}.sleep'
        if attr == 'wait' and recv != 'self':
            # Event/Condition/process waits block; `self.<x>.wait()`
            # chains land here too via recv='' — still blocking.
            return 'wait', f'{recv or "?"}.wait'
        if recv == 'subprocess':
            return 'subprocess', f'subprocess.{attr}'
        if recv == 'socket' and attr in ('socket', 'create_connection'):
            return 'network', f'socket.{attr}'
        if attr == 'urlopen' or recv in _NETWORK_RECVS:
            return 'network', f'{recv}.{attr}'.strip('.')
        if attr == 'connect' and recv in ('sqlite3', 'db_utils'):
            return 'db', f'{recv}.connect'
        if attr in ('execute', 'executemany', 'executescript',
                    'commit'):
            return 'db', f'.{attr}'
        if recv == 'os' and attr in _OS_FS_WRITE:
            return 'fs-write', f'os.{attr}'
        if recv == 'shutil':
            return 'fs-write', f'shutil.{attr}'
        if attr in _FILE_WRITE_ATTRS:
            return 'fs-write', f'.{attr}'
        if attr == 'run_in_parallel':
            return 'fanout', 'run_in_parallel'
        return None

    @staticmethod
    def _open_writes(call: ast.Call) -> bool:
        mode = None
        if len(call.args) > 1 and isinstance(call.args[1], ast.Constant):
            mode = call.args[1].value
        for kw in call.keywords:
            if kw.arg == 'mode' and isinstance(kw.value, ast.Constant):
                mode = kw.value.value
        return isinstance(mode, str) and \
            any(ch in mode for ch in _WRITE_MODE_CHARS)


def harvest_into(index, mod, rel_path: str, tree: ast.Module,
                 lines: List[str]) -> None:
    """Populate ``index.functions`` and ``mod.import_map`` from one
    shared tree (called by ``ProjectIndex.add_file`` — never parses)."""
    _harvest_imports(tree.body, mod.import_map)

    def one(fn: ast.AST, cls: Optional[str]) -> None:
        qual = f'{cls}.{fn.name}' if cls else fn.name
        node = FunctionNode(
            rel_path=rel_path, qual=qual, lineno=fn.lineno, cls=cls,
            exempt_all=_marker_covers(lines, fn.lineno))
        index.functions[(rel_path, qual)] = node
        _FunctionHarvester(node, mod.locks, lines).walk_body(
            fn.body, (), False, False)

    for top in tree.body:
        if isinstance(top, (ast.FunctionDef, ast.AsyncFunctionDef)):
            one(top, None)
        elif isinstance(top, ast.ClassDef):
            for sub in top.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    one(sub, top.name)


# ---- the graph --------------------------------------------------------------

Key = Tuple[str, str]          # (rel_path, qual)


class CallGraph:
    """Whole-program call graph over a :class:`ProjectIndex`'s
    harvested :class:`FunctionNode`\\ s. Edge resolution is lazy and
    memoized; ``unknown`` counts the dynamic call sites per node that
    no heuristic could resolve (the explicit soundness budget)."""

    def __init__(self, index) -> None:
        self.index = index
        self.functions: Dict[Key, FunctionNode] = index.functions
        self.unknown: Dict[Key, int] = {}
        self._edges: Dict[Key, List[Tuple[Key, CallSite]]] = {}
        # (rel_path, method name) → [quals] for the unique-local-method
        # fallback.
        self._methods: Dict[Tuple[str, str], List[str]] = {}
        for (rel, qual) in self.functions:
            if '.' in qual:
                cls, meth = qual.split('.', 1)
                del cls
                self._methods.setdefault((rel, meth), []).append(qual)
        self._safe: Optional[Dict[Key, Tuple[bool, Any]]] = None
        self._below_locks: Optional[Dict[Key, Set[str]]] = None
        self._below_prims: Optional[Dict[Key, Dict[str, Any]]] = None

    @classmethod
    def for_index(cls, index) -> 'CallGraph':
        graph = getattr(index, '_callgraph', None)
        if graph is None:
            graph = cls(index)
            index._callgraph = graph
        return graph

    # -- resolution ----------------------------------------------------------

    def _module_rel(self, dotted: str) -> Optional[str]:
        base = dotted.replace('.', '/')
        for rel in (f'{base}.py', f'{base}/__init__.py'):
            if rel in self.index.modules:
                return rel
        return None

    def _fn_in(self, rel: str, name: str) -> Optional[Key]:
        if (rel, name) in self.functions:
            return (rel, name)
        # Constructing a class resolves to its __init__ (a class with
        # no __init__ is a resolvable no-op leaf — dropped as external
        # by the caller).
        if (rel, f'{name}.__init__') in self.functions:
            return (rel, f'{name}.__init__')
        return None

    def resolve(self, key: Key, site: CallSite,
                strict: bool = False) -> Tuple[str, Optional[Key]]:
        """('fn', target) | ('external', None) | ('unknown', None).

        ``strict`` disables the unique-local-method heuristic: it
        over-approximates, which is the SAFE direction for the
        purity/lock closures (extra edges → extra findings) but
        unsound as a never-raise PROOF (a guessed-wrong target could
        certify a raising fallback) — proof consumers resolve
        strictly and treat the guess as unknown."""
        rel, _ = key
        node = self.functions[key]
        mod = self.index.modules.get(rel)
        imap = dict(getattr(mod, 'import_map', {}) or {})
        imap.update(node.imports)
        if site.kind == 'self':
            if node.cls is not None:
                target = self.functions.get(
                    (rel, f'{node.cls}.{site.name}'))
                if target is not None:
                    return 'fn', (rel, f'{node.cls}.{site.name}')
            return 'unknown', None   # inherited / dynamic attribute
        if site.kind == 'name':
            target = self._fn_in(rel, site.name)
            if target is not None:
                return 'fn', target
            dotted = imap.get(site.name)
            if dotted:
                parent, _, leaf = dotted.rpartition('.')
                parent_rel = self._module_rel(parent) if parent else None
                if parent_rel is not None:
                    target = self._fn_in(parent_rel, leaf)
                    if target is not None:
                        return 'fn', target
                return 'external', None
            return 'external', None   # builtin or inherited global
        if site.kind == 'recv':
            dotted = imap.get(site.recv)
            if dotted:
                target_rel = self._module_rel(dotted)
                if target_rel is not None:
                    target = self._fn_in(target_rel, site.name)
                    if target is not None:
                        return 'fn', target
                    return 'unknown', None   # re-export / dynamic
                return 'external', None      # time.sleep, jax...
        if strict:
            return 'unknown', None
        return self._unique_method(rel, site)

    def _unique_method(self, rel: str,
                       site: CallSite) -> Tuple[str, Optional[Key]]:
        quals = self._methods.get((rel, site.name), [])
        if len(quals) == 1:
            return 'fn', (rel, quals[0])
        return 'unknown', None

    def edges(self, key: Key) -> List[Tuple[Key, CallSite]]:
        cached = self._edges.get(key)
        if cached is not None:
            return cached
        out: List[Tuple[Key, CallSite]] = []
        unknown = 0
        for site in self.functions[key].calls:
            verdict, target = self.resolve(key, site)
            if verdict == 'fn' and target is not None:
                out.append((target, site))
            elif verdict == 'unknown':
                unknown += 1
        self._edges[key] = out
        self.unknown[key] = unknown
        return out

    # -- closures + chains ---------------------------------------------------

    def closure(self, entries: List[Key],
                skip_modules: Tuple[str, ...] = (),
                follow_spawn: bool = False
                ) -> Dict[Key, Optional[Tuple[Key, CallSite]]]:
        """BFS from `entries`; returns {node: (parent, via-site)} with
        None for the entries themselves. BFS ⇒ the recorded parent
        chain is a shortest entry→node path."""
        parents: Dict[Key, Optional[Tuple[Key, CallSite]]] = {}
        queue: List[Key] = []
        for entry in entries:
            if entry in self.functions and entry not in parents:
                parents[entry] = None
                queue.append(entry)
        i = 0
        while i < len(queue):
            key = queue[i]
            i += 1
            for target, site in self.edges(key):
                if site.spawn and not follow_spawn:
                    continue
                if target[0] in skip_modules:
                    continue
                if target not in parents:
                    parents[target] = (key, site)
                    queue.append(target)
        return parents

    def chain(self, parents, key: Key) -> List[Tuple[Key, int]]:
        """[(node, call lineno into the NEXT node)] entry-first; the
        last element's lineno is 0 (it is the endpoint)."""
        rev: List[Tuple[Key, int]] = [(key, 0)]
        cur = key
        while parents.get(cur) is not None:
            parent, site = parents[cur]
            rev.append((parent, site.lineno))
            cur = parent
        rev.reverse()
        return rev

    def render_chain(self, parents, key: Key) -> List[str]:
        out = []
        chain = self.chain(parents, key)
        for i, (node_key, lineno) in enumerate(chain):
            rel, qual = node_key
            arrow = '' if i == 0 else '-> '
            at = f' (calls next at {rel}:{lineno})' if lineno else ''
            out.append(f'{arrow}{qual} [{rel}:'
                       f'{self.functions[node_key].lineno}]{at}')
        return out

    # -- fixpoints -----------------------------------------------------------

    def below_locks(self) -> Dict[Key, Set[str]]:
        """Locks acquired anywhere in each node's transitive closure
        (spawn edges excluded — a new thread starts lock-free)."""
        if self._below_locks is not None:
            return self._below_locks
        below = {key: {a.lock for a in node.lock_acqs}
                 for key, node in self.functions.items()}
        changed = True
        while changed:
            changed = False
            for key in self.functions:
                for target, site in self.edges(key):
                    if site.spawn:
                        continue
                    extra = below[target] - below[key]
                    if extra:
                        below[key] |= extra
                        changed = True
        self._below_locks = below
        return below

    def below_prims(self
                    ) -> Dict[Key, Dict[Tuple[str, str],
                                        Tuple[Key, Any]]]:
        """(kind, owner module) → one (owner, PrimitiveSite) witness
        reachable from each node (spawn edges excluded). Keyed per
        OWNER MODULE, not just kind — the lock-order rule exempts a
        db primitive in the lock's own module but not a cross-module
        one, so a same-module witness must never shadow a reachable
        cross-module violation of the same kind. ``# hotpath ok:``
        exempt sites are INCLUDED — the marker bounds a site's
        hot-path cost, not the time a lock stays held over it; each
        witness carries its PrimitiveSite, so consumers that do want
        to honor exemptions can filter on ``prim.exempt``."""
        if self._below_prims is not None:
            return self._below_prims
        below: Dict[Key, Dict[Tuple[str, str], Tuple[Key, Any]]] = {}
        for key, node in self.functions.items():
            own: Dict[Tuple[str, str], Tuple[Key, Any]] = {}
            for prim in node.primitives:
                own.setdefault((prim.kind, key[0]), (key, prim))
            below[key] = own
        changed = True
        while changed:
            changed = False
            for key in self.functions:
                for target, site in self.edges(key):
                    if site.spawn:
                        continue
                    for wkey, witness in below[target].items():
                        if wkey not in below[key]:
                            below[key][wkey] = witness
                            changed = True
        self._below_prims = below
        return below

    # -- transitive no-raise -------------------------------------------------

    # External calls accepted inside fallback arms: clock reads cannot
    # realistically raise and appear throughout the recording planes.
    NO_RAISE_EXTERNAL = frozenset({
        'time.time', 'time.monotonic', 'time.perf_counter',
        'isinstance', 'id', 'bool',
    })

    def no_raise_safe(self) -> Dict[Key, Tuple[bool, Any]]:
        """{node: (safe, reason)} — `safe` means the function provably
        cannot raise: no risky construct outside a broad try, and
        every unprotected call resolves to a transitively-safe
        function (or an allowlisted external). reason is
        ('risky', line, what) or ('call', site, target-or-None)."""
        if self._safe is not None:
            return self._safe
        verdicts: Dict[Key, Tuple[bool, Any]] = {}
        for key, node in self.functions.items():
            if node.risky_line is not None:
                verdicts[key] = (
                    False, ('risky', node.risky_line, node.risky_what))
            else:
                verdicts[key] = (True, None)
        # Iterate downward: a call to an unsafe/unresolved function
        # flips the caller unsafe; repeat to fixpoint. Resolution is
        # STRICT — the unique-method guess must never certify a
        # proof.
        changed = True
        while changed:
            changed = False
            for key, node in self.functions.items():
                if not verdicts[key][0]:
                    continue
                for site in node.unprotected_calls():
                    verdict, target = self.resolve(key, site,
                                                   strict=True)
                    if verdict == 'external':
                        label = f'{site.recv}.{site.name}' if site.recv \
                            else site.name
                        if label in self.NO_RAISE_EXTERNAL:
                            continue
                        verdicts[key] = (False, ('call', site, None))
                        changed = True
                        break
                    if verdict == 'unknown':
                        verdicts[key] = (False, ('call', site, None))
                        changed = True
                        break
                    if not verdicts[target][0]:
                        verdicts[key] = (False, ('call', site, target))
                        changed = True
                        break
        self._safe = verdicts
        return verdicts

    def explain_unsafe(self, key: Key, limit: int = 8) -> List[str]:
        """Why `key` is not provably no-raise: the call chain down to
        the first risky construct."""
        verdicts = self.no_raise_safe()
        out: List[str] = []
        cur: Optional[Key] = key
        seen = set()
        while cur is not None and cur not in seen and len(out) < limit:
            seen.add(cur)
            safe, reason = verdicts.get(cur, (True, None))
            if safe or reason is None:
                break
            rel, qual = cur
            if reason[0] == 'risky':
                out.append(f'{qual} [{rel}:{reason[1]}] has a '
                           f'{reason[2]} outside any broad try')
                break
            site, target = reason[1], reason[2]
            label = f'{site.recv}.{site.name}' if site.recv \
                else site.name
            if target is None:
                out.append(f'{qual} [{rel}:{site.lineno}] calls '
                           f'{label} which cannot be resolved/proven')
                break
            out.append(f'{qual} [{rel}:{site.lineno}] calls '
                       f'{target[1]}')
            cur = target
        return out
