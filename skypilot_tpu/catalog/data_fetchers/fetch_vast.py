"""Generate the Vast.ai catalog CSV (role of the reference's
sky/catalog/vast_catalog.py construction).

Vast is a live marketplace, so any catalog is an approximation: with a
$VAST_API_KEY and egress, rows come from a `/bundles/` offer sweep
aggregated per (gpu, count, country) at the median on-demand price;
offline (this environment) the checked-in CSV is a static snapshot of
typical marketplace medians. The provisioner re-searches live offers
at launch, so catalog staleness only affects optimizer ranking, not
correctness.

InstanceType grammar: `{count}x_{ACC}` (same as runpod).

Run: python -m skypilot_tpu.catalog.data_fetchers.fetch_vast
"""
from __future__ import annotations

import csv
import os
from typing import List, Tuple

# (acc_name, acc_mem_gib, vcpus_per_gpu, mem_gib_per_gpu,
#  median_price_per_gpu, median_bid_per_gpu)
_SKUS: List[Tuple[str, float, float, float, float, float]] = [
    ('RTX3090', 24, 8, 32, 0.22, 0.11),
    ('RTX4090', 24, 12, 48, 0.35, 0.18),
    ('RTX5090', 32, 14, 64, 0.55, 0.28),
    ('RTXA6000', 48, 10, 48, 0.45, 0.23),
    ('L40S', 48, 12, 62, 0.67, 0.34),
    ('A100-80GB', 80, 12, 96, 1.10, 0.55),
    ('H100', 80, 16, 128, 1.93, 0.97),
    ('H100-SXM', 80, 20, 128, 2.30, 1.15),
    ('H200-SXM', 141, 24, 192, 2.90, 1.45),
]

# Two-letter country codes (Vast geolocations end in one; the
# provisioner matches on that suffix).
_REGIONS = ['US', 'CA', 'DE', 'SE', 'JP']

HEADER = ['InstanceType', 'AcceleratorName', 'AcceleratorCount', 'vCPUs',
          'MemoryGiB', 'AcceleratorMemoryGiB', 'Price', 'SpotPrice',
          'Region', 'AvailabilityZone']


def rows_from_api() -> List[List[str]]:
    """Live medians from an offer sweep (requires key + egress)."""
    import statistics
    from skypilot_tpu.clouds.vast import ACC_TO_GPU_NAME
    from skypilot_tpu.provision.vast import rest
    t = rest.Transport()
    out = []
    for acc, gpu_name in ACC_TO_GPU_NAME.items():
        for count in (1, 2, 4, 8):
            reply = t.call('PUT', '/bundles/', {'q': {
                'verified': {'eq': True}, 'rentable': {'eq': True},
                'gpu_name': {'eq': gpu_name},
                'num_gpus': {'eq': count},
                'order': [['dph_total', 'asc']], 'type': 'on-demand'}})
            offers = reply.get('offers', [])
            if not offers:
                continue
            by_cc = {}
            for offer in offers:
                cc = (offer.get('geolocation') or 'US')[-2:]
                by_cc.setdefault(cc, []).append(offer)
            for cc, group in sorted(by_cc.items()):
                price = statistics.median(
                    o['dph_total'] for o in group)
                bid = statistics.median(
                    o.get('min_bid', price / 2) for o in group)
                sample = group[0]
                out.append([
                    f'{count}x_{acc}', acc, f'{count}',
                    f"{sample.get('cpu_cores_effective', 8 * count):g}",
                    f"{sample.get('cpu_ram', 0) / 1024:g}",
                    f"{sample.get('gpu_ram', 0) / 1024:g}",
                    f'{price:.4f}', f'{bid:.4f}', cc, cc])
    if not out:
        raise RuntimeError('offer sweep returned nothing')
    return out


def rows_static() -> List[List[str]]:
    out = []
    for (acc, acc_mem, vcpus, mem, price, bid) in _SKUS:
        for count in (1, 2, 4, 8):
            for region in _REGIONS:
                out.append([
                    f'{count}x_{acc}', acc, f'{count}',
                    f'{vcpus * count:g}', f'{mem * count:g}',
                    f'{acc_mem:g}', f'{price * count:.4f}',
                    f'{bid * count:.4f}', region, region])
    return out


def main() -> None:
    try:
        data = rows_from_api()
        source = 'live API'
    except Exception:  # pylint: disable=broad-except
        data = rows_static()
        source = 'static snapshot'
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, 'data', 'vast', 'catalog.csv')
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, 'w', newline='', encoding='utf-8') as f:
        writer = csv.writer(f)
        writer.writerow(HEADER)
        writer.writerows(data)
    print(f'Wrote {path} ({source})')


if __name__ == '__main__':
    main()
