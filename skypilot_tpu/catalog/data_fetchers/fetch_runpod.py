"""Generate the RunPod catalog CSV (twin of
sky/catalog/data_fetchers/fetch_runpod... — the reference vendors a
prebuilt catalog for RunPod; this repo generates its own).

With a $RUNPOD_API_KEY and egress, rows come live from the GraphQL
`gpuTypes` query (securePrice/communitySpotPrice per GPU); offline
(this environment) the checked-in CSV is generated from a static
snapshot of RunPod's published secure-cloud price sheet. The
interruptible ("spot") market price is the community spot rate.

InstanceType grammar: `{count}x_{ACC}` — pods are sized by GPU count
only; vCPU/RAM scale with the GPU (snapshot below uses RunPod's
per-GPU allocations).

Run: python -m skypilot_tpu.catalog.data_fetchers.fetch_runpod
"""
from __future__ import annotations

import csv
import os
from typing import List, Tuple

# (acc_name, acc_mem_gib, vcpus_per_gpu, mem_gib_per_gpu,
#  price_per_gpu, spot_price_per_gpu, max_count)
_SKUS: List[Tuple[str, float, float, float, float, float, int]] = [
    ('A40', 48, 9, 48, 0.39, 0.20, 8),
    ('L4', 24, 12, 50, 0.43, 0.22, 8),
    ('L40S', 48, 16, 62, 0.86, 0.43, 8),
    ('RTX4090', 24, 16, 62, 0.69, 0.35, 8),
    ('RTX5090', 32, 16, 94, 0.89, 0.45, 8),
    ('RTXA6000', 48, 9, 50, 0.76, 0.38, 8),
    ('RTX6000-Ada', 48, 16, 62, 0.77, 0.39, 8),
    ('A100-80GB', 80, 8, 117, 1.64, 0.82, 8),
    ('A100-80GB-SXM', 80, 16, 125, 1.89, 0.95, 8),
    ('H100', 80, 16, 188, 2.39, 1.20, 8),
    ('H100-SXM', 80, 20, 125, 2.99, 1.50, 8),
    ('H200-SXM', 141, 24, 251, 3.59, 1.80, 8),
    ('B200', 180, 28, 283, 5.99, 2.99, 8),
    ('MI300X', 192, 24, 283, 2.49, 1.25, 8),
]

_REGIONS = ['US-CA-2', 'US-GA-1', 'US-TX-3', 'CA-MTL-1', 'EU-RO-1',
            'EU-SE-1', 'AP-JP-1']

HEADER = ['InstanceType', 'AcceleratorName', 'AcceleratorCount', 'vCPUs',
          'MemoryGiB', 'AcceleratorMemoryGiB', 'Price', 'SpotPrice',
          'Region', 'AvailabilityZone']

_GPU_TYPES_QUERY = """
query GpuTypes {
  gpuTypes {
    id
    displayName
    memoryInGb
    securePrice
    communitySpotPrice
    maxGpuCount
  }
}
"""


def rows_from_api() -> List[List[str]]:
    """Live rows from the gpuTypes query (requires key + egress)."""
    from skypilot_tpu.clouds.runpod import ACC_TO_GPU_ID
    from skypilot_tpu.provision.runpod import rest
    id_to_acc = {v: k for k, v in ACC_TO_GPU_ID.items()}
    # The gpuTypes query reports GPU VRAM, not the host's vCPU/RAM
    # allocation; host specs come from the per-SKU snapshot (RunPod's
    # published per-GPU allocations) keyed by accelerator.
    host_specs = {acc: (vcpus, mem)
                  for (acc, _, vcpus, mem, _, _, _) in _SKUS}
    reply = rest.Transport().call(_GPU_TYPES_QUERY)
    out = []
    for gpu in reply.get('gpuTypes', []):
        acc = id_to_acc.get(gpu['id'])
        price = gpu.get('securePrice')
        if acc is None or not price:
            continue
        spot = gpu.get('communitySpotPrice') or 0
        acc_mem = gpu.get('memoryInGb', 0)
        vcpus, host_mem = host_specs.get(acc, (8, 2 * acc_mem))
        for count in (1, 2, 4, 8):
            if count > gpu.get('maxGpuCount', 8):
                continue
            for region in _REGIONS:
                out.append([
                    f'{count}x_{acc}', acc, f'{count}',
                    f'{vcpus * count:g}', f'{host_mem * count:g}',
                    f'{acc_mem:g}',
                    f'{price * count:.4f}', f'{spot * count:.4f}',
                    region, region])
    return out


def rows_static() -> List[List[str]]:
    out = []
    for (acc, acc_mem, vcpus, mem, price, spot, max_count) in _SKUS:
        for count in (1, 2, 4, 8):
            if count > max_count:
                continue
            for region in _REGIONS:
                out.append([
                    f'{count}x_{acc}', acc, f'{count}',
                    f'{vcpus * count:g}', f'{mem * count:g}',
                    f'{acc_mem:g}', f'{price * count:.4f}',
                    f'{spot * count:.4f}', region, region])
    return out


def main() -> None:
    try:
        data = rows_from_api()
        source = 'live API'
    except Exception:  # pylint: disable=broad-except
        data = rows_static()
        source = 'static snapshot'
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, 'data', 'runpod', 'catalog.csv')
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, 'w', newline='', encoding='utf-8') as f:
        writer = csv.writer(f)
        writer.writerow(HEADER)
        writer.writerows(data)
    print(f'Wrote {path} ({source})')


if __name__ == '__main__':
    main()
