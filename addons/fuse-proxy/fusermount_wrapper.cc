// fusermount-wrapper: pre-mount /dev/fuse for libfuse-direct adapters.
//
// C++ twin of addons/fuse-proxy/cmd/fusermount-wrapper/main.go
// (reference). Adapters that mount the FUSE device themselves (e.g.
// blobfuse2, rclone) never fall back to fusermount, but opening
// /dev/fuse needs privilege. The wrapper asks fusermount-server to do
// the mount and hands the resulting fd to the adapter as /dev/fd/N —
// libfuse detects an already-mounted fd at that path and uses it as-is.
//
// Usage:
//   fusermount-wrapper <mountpoint> [-o opts] -- <adapter> [args...]
// Every literal "{}" in the adapter args is replaced by the mountpoint
// argument (/dev/fd/N).
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include <unistd.h>

#include "common.hpp"

namespace fp = fuseproxy;

int main(int argc, char** argv) {
  std::string mountpoint;
  std::string options;
  int i = 1;
  std::vector<char*> adapter;
  for (; i < argc; ++i) {
    if (std::strcmp(argv[i], "-o") == 0 && i + 1 < argc) {
      options = argv[++i];
    } else if (std::strcmp(argv[i], "--") == 0) {
      ++i;
      break;
    } else if (mountpoint.empty()) {
      mountpoint = argv[i];
    } else {
      std::fprintf(stderr, "fusermount-wrapper: unexpected arg %s\n",
                   argv[i]);
      return 2;
    }
  }
  for (; i < argc; ++i) adapter.push_back(argv[i]);
  if (mountpoint.empty() || adapter.empty()) {
    std::fprintf(stderr,
                 "usage: fusermount-wrapper <mountpoint> [-o opts] -- "
                 "<adapter> [args...]\n");
    return 2;
  }

  fp::Request req;
  req.mode = fp::kModeMount;
  req.want_fd = true;
  req.args = {mountpoint, options};

  int sock = fp::ConnectTo(fp::DefaultSocketPath());
  if (sock < 0) {
    std::fprintf(stderr, "fusermount-wrapper: cannot connect to %s\n",
                 fp::DefaultSocketPath());
    return 1;
  }
  if (!fp::SendRequest(sock, req)) {
    std::fprintf(stderr, "fusermount-wrapper: send failed\n");
    return 1;
  }
  fp::Response resp;
  if (!fp::RecvResponse(sock, &resp) || resp.code != 0 || resp.fd < 0) {
    std::fprintf(stderr, "fusermount-wrapper: mount failed: %s\n",
                 resp.message.c_str());
    return resp.code ? resp.code : 1;
  }
  // Keep the fd open across exec; clear CLOEXEC.
  // (SCM_RIGHTS fds arrive without CLOEXEC by default, but be explicit.)
  char devfd[32];
  std::snprintf(devfd, sizeof(devfd), "/dev/fd/%d", resp.fd);

  std::vector<std::string> final_args;
  for (char* a : adapter) {
    std::string s(a);
    if (s == "{}") s = devfd;
    final_args.push_back(std::move(s));
  }
  std::vector<char*> exec_argv;
  for (auto& s : final_args) exec_argv.push_back(&s[0]);
  exec_argv.push_back(nullptr);
  ::execvp(exec_argv[0], exec_argv.data());
  std::fprintf(stderr, "fusermount-wrapper: exec %s failed: %s\n",
               exec_argv[0], std::strerror(errno));
  return 127;
}
