"""End-to-end launch → gang execute → logs → exec → teardown on the fake
cloud. This is the harness the reference lacks (SURVEY §4.5: no fake
multi-node backend) — every host is a real local process.
"""
import json
import os
import time

import pytest

from skypilot_tpu import Resources, Task
from skypilot_tpu import exceptions
from skypilot_tpu import execution
from skypilot_tpu import state
from skypilot_tpu.agent import job_lib


pytestmark = pytest.mark.slow  # heavy tier: subprocess e2e / jit compiles


def _wait_status(backend, handle, job_id, timeout=20):
    deadline = time.time() + timeout
    while time.time() < deadline:
        status = backend.get_job_status(handle, job_id)
        if status is not None and status.is_terminal():
            return status
        time.sleep(0.2)
    raise TimeoutError


class TestLaunch:

    def test_launch_single_host(self, fake_cluster_env):
        task = Task('hello', run='echo "hello from $XSKY_HOST_RANK"')
        task.set_resources(Resources(accelerators='tpu-v5e-8'))
        job_id, handle = execution.launch(task, cluster_name='t1')
        assert job_id == 1
        record = state.get_cluster_from_name('t1')
        assert record['status'] == state.ClusterStatus.UP
        from skypilot_tpu.backends import tpu_gang_backend
        backend = tpu_gang_backend.TpuGangBackend()
        logs = backend.tail_logs(handle, job_id, follow=False)
        assert 'hello from 0' in logs

    def test_launch_opens_and_cleans_up_ports(self, fake_cluster_env):
        """Resources(ports=…) reaches provision.open_ports during
        launch and cleanup_ports at teardown (VERDICT r4: the dispatch
        existed but nothing in the launch path ever called it)."""
        task = Task('svc', run='echo up')
        task.set_resources(Resources(accelerators='tpu-v5e-8',
                                     ports=[8080, '4000-4100']))
        _, handle = execution.launch(task, cluster_name='tports')
        assert fake_cluster_env.opened_ports('tports') == \
            ['4000-4100', '8080']
        from skypilot_tpu.backends import tpu_gang_backend
        backend = tpu_gang_backend.TpuGangBackend()
        backend.teardown(handle, terminate=True)
        assert fake_cluster_env.opened_ports('tports') == []

    def test_launch_mounts_volumes_before_job(self, fake_cluster_env,
                                              tmp_path):
        """resources.volumes → deploy vars → ClusterInfo.mount_commands
        → executed on every host during runtime setup, BEFORE the job
        runs (the job itself proves the path is ready)."""
        mnt = tmp_path / 'mnt' / 'vol'
        task = Task('vols', run=f'test -e {mnt}/.xsky-vol-v1 && echo '
                                'vol-visible')
        task.set_resources(Resources(
            accelerators='tpu-v5e-8',
            volumes=[{'name': 'v1', 'path': str(mnt)}]))
        job_id, handle = execution.launch(task, cluster_name='tvol')
        from skypilot_tpu.backends import tpu_gang_backend
        backend = tpu_gang_backend.TpuGangBackend()
        assert _wait_status(backend, handle, job_id) == \
            job_lib.JobStatus.SUCCEEDED
        assert 'vol-visible' in backend.tail_logs(handle, job_id,
                                                  follow=False)
        assert (mnt / '.xsky-vol-v1').exists()

    def test_launch_streams_logs_live(self, fake_cluster_env, capsys):
        """The launch wait live-tails run.log via the one-call `watch`
        verb: job output must land on stdout BEFORE launch returns, not
        only in a post-hoc tail."""
        task = Task('streamer', run='echo live-line-1; echo live-line-2')
        task.set_resources(Resources(accelerators='tpu-v5e-8'))
        execution.launch(task, cluster_name='tstream')
        out = capsys.readouterr().out
        assert 'live-line-1' in out and 'live-line-2' in out

    def test_watch_verb_batches_status_and_log(self, fake_cluster_env):
        """`job_cli watch` returns status + next log chunk in one call,
        and successive offsets never re-deliver bytes."""
        task = Task('w', run='echo chunk-one')
        task.set_resources(Resources(accelerators='tpu-v5e-8'))
        job_id, handle = execution.launch(task, cluster_name='twatch')
        from skypilot_tpu.backends import tpu_gang_backend
        backend = tpu_gang_backend.TpuGangBackend()
        rec = backend._watch_job(handle, job_id, 0)
        assert rec['status'] == 'SUCCEEDED'
        assert b'chunk-one' in rec['log']
        rec2 = backend._watch_job(handle, job_id, rec['offset'])
        assert rec2['log'] == b''
        assert rec2['offset'] == rec['offset']

    def test_gang_env_on_pod(self, fake_cluster_env):
        """All 4 hosts of a v5e-32 slice run, each with correct rank env."""
        task = Task(
            'envdump',
            run='echo RANK=$XSKY_HOST_RANK/$XSKY_NUM_HOSTS '
                'TPU_ID=$TPU_WORKER_ID NODES=$XSKY_NUM_NODES '
                'COORD=$XSKY_COORDINATOR_ADDRESS')
        task.set_resources(Resources(accelerators='tpu-v5e-32'))
        job_id, handle = execution.launch(task, cluster_name='pod1')
        root = handle.head_runtime_root
        log_dir = os.path.join(root, 'logs', f'job-{job_id}')
        contents = {}
        for rank in range(4):
            with open(os.path.join(log_dir, f'host-{rank}.log')) as f:
                contents[rank] = f.read()
        for rank in range(4):
            assert f'RANK={rank}/4' in contents[rank]
            assert f'TPU_ID={rank}' in contents[rank]
        # Same coordinator everywhere.
        coords = {c.split('COORD=')[1].strip()
                  for c in contents.values()}
        assert len(coords) == 1

    def test_gang_failure_kills_all(self, fake_cluster_env):
        """One host exiting non-zero fails the job (all-or-nothing)."""
        task = Task(
            'failing',
            run='if [ "$XSKY_HOST_RANK" = "1" ]; then exit 3; fi; '
                'sleep 30')
        task.set_resources(Resources(accelerators='tpu-v5e-32'))
        t0 = time.time()
        with pytest.raises(exceptions.JobExitNonZeroError):
            execution.launch(task, cluster_name='failpod')
        # Must not wait out the sleep 30 on the healthy hosts.
        assert time.time() - t0 < 25

    def test_exec_on_existing_cluster(self, fake_cluster_env):
        task = Task('first', run='echo one')
        task.set_resources(Resources(accelerators='tpu-v5e-8'))
        job1, handle = execution.launch(task, cluster_name='reuse')
        task2 = Task('second', run='echo two')
        task2.set_resources(Resources(accelerators='tpu-v5e-8'))
        job2, _ = execution.exec(task2, cluster_name='reuse')
        assert job2 == job1 + 1

    def test_exec_mismatched_resources(self, fake_cluster_env):
        task = Task('first', run='echo one')
        task.set_resources(Resources(accelerators='tpu-v5e-8'))
        execution.launch(task, cluster_name='small')
        big = Task('big', run='echo big')
        big.set_resources(Resources(accelerators='tpu-v5p-64'))
        with pytest.raises(exceptions.ResourcesMismatchError):
            execution.exec(big, cluster_name='small')

    def test_exec_on_missing_cluster(self, fake_cluster_env):
        t = Task(run='echo x')
        with pytest.raises(exceptions.ClusterDoesNotExist):
            execution.exec(t, cluster_name='ghost')

    def test_setup_failure_raises(self, fake_cluster_env):
        task = Task('badsetup', setup='exit 7', run='echo never')
        task.set_resources(Resources(accelerators='tpu-v5e-8'))
        with pytest.raises(exceptions.ClusterSetUpError):
            execution.launch(task, cluster_name='badsetup')

    def test_workdir_sync(self, fake_cluster_env, tmp_path):
        workdir = tmp_path / 'proj'
        workdir.mkdir()
        (workdir / 'data.txt').write_text('payload42')
        task = Task('wd', run='cat sky_workdir/data.txt',
                    workdir=str(workdir))
        task.set_resources(Resources(accelerators='tpu-v5e-8'))
        job_id, handle = execution.launch(task, cluster_name='wd1')
        from skypilot_tpu.backends import tpu_gang_backend
        backend = tpu_gang_backend.TpuGangBackend()
        assert 'payload42' in backend.tail_logs(handle, job_id, False)

    def test_teardown_removes_cluster(self, fake_cluster_env):
        fake = fake_cluster_env
        task = Task('gone', run='echo bye')
        task.set_resources(Resources(accelerators='tpu-v5e-8'))
        _, handle = execution.launch(task, cluster_name='gone')
        from skypilot_tpu.backends import tpu_gang_backend
        backend = tpu_gang_backend.TpuGangBackend()
        backend.teardown(handle, terminate=True)
        assert state.get_cluster_from_name('gone') is None
        assert not fake.cluster_exists('gone')

    def test_stop_multihost_tpu_refused(self, fake_cluster_env):
        task = Task('pod', run='echo hi')
        task.set_resources(Resources(accelerators='tpu-v5e-32'))
        _, handle = execution.launch(task, cluster_name='pod2')
        from skypilot_tpu.backends import tpu_gang_backend
        backend = tpu_gang_backend.TpuGangBackend()
        with pytest.raises(exceptions.NotSupportedError):
            backend.teardown(handle, terminate=False)

    def test_fifo_queue_order(self, fake_cluster_env):
        """Second job queues while the first runs; runs after it."""
        task = Task('slow', run='sleep 1.2; echo done1')
        task.set_resources(Resources(accelerators='tpu-v5e-8'))
        job1, handle = execution.launch(task, cluster_name='q1',
                                        detach_run=True)
        fast = Task('fast', run='echo done2')
        fast.set_resources(Resources(accelerators='tpu-v5e-8'))
        job2, _ = execution.exec(fast, cluster_name='q1', detach_run=True)
        from skypilot_tpu.backends import tpu_gang_backend
        backend = tpu_gang_backend.TpuGangBackend()
        s1 = _wait_status(backend, handle, job1)
        s2 = _wait_status(backend, handle, job2)
        assert s1 == job_lib.JobStatus.SUCCEEDED
        assert s2 == job_lib.JobStatus.SUCCEEDED
        queue = backend.get_job_queue(handle)
        j1 = next(j for j in queue if j['job_id'] == job1)
        j2 = next(j for j in queue if j['job_id'] == job2)
        assert j2['started_at'] >= j1['ended_at']

    def test_cancel_running_job(self, fake_cluster_env):
        task = Task('cancelme', run='sleep 60')
        task.set_resources(Resources(accelerators='tpu-v5e-8'))
        job_id, handle = execution.launch(task, cluster_name='c2',
                                          detach_run=True)
        from skypilot_tpu.backends import tpu_gang_backend
        backend = tpu_gang_backend.TpuGangBackend()
        # Wait for RUNNING, then cancel.
        deadline = time.time() + 10
        while time.time() < deadline:
            if backend.get_job_status(handle, job_id) == \
                    job_lib.JobStatus.RUNNING:
                break
            time.sleep(0.2)
        backend.cancel_jobs(handle, [job_id])
        status = _wait_status(backend, handle, job_id)
        assert status == job_lib.JobStatus.CANCELLED

    def test_autostop_lifecycle(self, fake_cluster_env):
        """Agent-side autostop teardown: the daemon tick must actually
        release the cloud resource (VERDICT r3 #6 — not just write a
        marker). The fake cloud is driveable from on-host, so the tick
        terminates the cluster in the provider store directly."""
        from skypilot_tpu.agent import daemon
        from skypilot_tpu.provision.fake import instance as fake_instance
        task = Task('idle', run='echo done')
        task.set_resources(Resources(accelerators='tpu-v5e-8'))
        _, handle = execution.launch(
            task, cluster_name='a1', idle_minutes_to_autostop=0, down=True)
        root = handle.head_runtime_root
        record = state.get_cluster_from_name('a1')
        assert record['autostop'] == 0
        assert fake_instance.query_instances('a1', {})
        # Tick the agent: idle 0-minute deadline passed → the agent
        # terminates its own cluster via the provider API.
        daemon.run_forever(root=root, interval_s=0, max_ticks=1)
        assert fake_instance.query_instances('a1', {}) == {}

    def test_autostop_marker_fallback(self, fake_cluster_env,
                                      monkeypatch):
        """Providers that can't be driven from on-host (or with
        self-teardown disabled) fall back to the marker file the
        control plane polls (pull model)."""
        from skypilot_tpu.agent import autostop_lib, daemon
        monkeypatch.setenv('XSKY_AGENT_NO_SELF_TEARDOWN', '1')
        task = Task('idle', run='echo done')
        task.set_resources(Resources(accelerators='tpu-v5e-8'))
        _, handle = execution.launch(
            task, cluster_name='a2', idle_minutes_to_autostop=0,
            down=True)
        root = handle.head_runtime_root
        daemon.run_forever(root=root, interval_s=0, max_ticks=1)
        marker = os.path.join(root, 'autostop_triggered.json')
        assert os.path.exists(marker)
        with open(marker) as f:
            assert json.load(f)['down'] is True
        # The deadline must not re-fire: config was cleared.
        assert autostop_lib.get_autostop(root) is None


class TestBootstrap:
    """Remote-runtime self-bootstrap: a fresh host has nothing
    preinstalled — the backend must ship its own wheel and install it
    (twin of sky/backends/wheel_utils.py + instance_setup.py:540)."""

    def test_launch_bootstraps_host_without_repo_pythonpath(
            self, fake_cluster_env, monkeypatch):
        import subprocess
        monkeypatch.setenv('XSKY_BOOTSTRAP_LOCAL', '1')
        task = Task('boot', run='echo bootstrapped-ok')
        task.set_resources(Resources(accelerators='tpu-v5e-8'))
        job_id, handle = execution.launch(task, cluster_name='boot1')
        from skypilot_tpu.backends import tpu_gang_backend
        backend = tpu_gang_backend.TpuGangBackend()
        assert 'bootstrapped-ok' in backend.tail_logs(handle, job_id, False)
        # Agent commands must not lean on the control plane's checkout.
        assert 'PYTHONPATH' not in backend._agent_env(handle)
        assert '/venv/bin/python' in backend._head_python(handle)
        # The host venv imports the package from its own site-packages
        # even with no repo PYTHONPATH in the environment.
        venv_py = os.path.join(handle.head_runtime_root, 'venv', 'bin',
                               'python')
        assert os.path.exists(venv_py)
        clean_env = {k: v for k, v in os.environ.items()
                     if k != 'PYTHONPATH'}
        proc = subprocess.run(
            [venv_py, '-c', 'import skypilot_tpu; '
             'print(skypilot_tpu.__file__)'],
            capture_output=True, text=True, env=clean_env, check=False,
            cwd='/')  # neutral cwd: `-c` puts cwd on sys.path
        assert proc.returncode == 0, proc.stderr
        assert 'site-packages' in proc.stdout
        from skypilot_tpu.backends import tpu_gang_backend as tgb
        assert tgb._REPO_ROOT not in proc.stdout

    def test_bootstrap_is_idempotent(self, fake_cluster_env, monkeypatch):
        monkeypatch.setenv('XSKY_BOOTSTRAP_LOCAL', '1')
        task = Task('boot2', run='echo ok')
        task.set_resources(Resources(accelerators='tpu-v5e-8'))
        _, handle = execution.launch(task, cluster_name='boot2')
        from skypilot_tpu.backends import tpu_gang_backend
        backend = tpu_gang_backend.TpuGangBackend()
        root = handle.head_runtime_root
        marker = os.path.join(root, 'wheel_hash')
        with open(marker) as f:
            first_hash = f.read().strip()
        venv_py = os.path.join(root, 'venv', 'bin', 'python')
        mtime = os.path.getmtime(venv_py)
        # Re-running setup must skip both venv creation and pip install.
        backend._setup_runtime(handle)
        with open(marker) as f:
            assert f.read().strip() == first_hash
        assert os.path.getmtime(venv_py) == mtime


class TestEndpoints:

    def test_endpoints_map_ports_to_head_ip(self, fake_cluster_env):
        """`xsky endpoints` (query_ports twin): opened ports resolve to
        reachable URLs on the head host's feasible IP."""
        from skypilot_tpu import core
        task = Task('svc', run='echo up')
        task.set_resources(Resources(accelerators='tpu-v5e-8',
                                     ports=[8080, '9000-9001']))
        _, handle = execution.launch(task, cluster_name='teps')
        head_ip = handle.cluster_info.get_head_instance().get_feasible_ip()
        eps = core.endpoints('teps')
        assert eps == {8080: f'http://{head_ip}:8080',
                       9000: f'http://{head_ip}:9000',
                       9001: f'http://{head_ip}:9001'}
        assert core.endpoints('teps', port=8080) == {
            8080: f'http://{head_ip}:8080'}
        # No ports requested → empty.
        task2 = Task('plain', run='echo hi')
        task2.set_resources(Resources(accelerators='tpu-v5e-8'))
        execution.launch(task2, cluster_name='teps2')
        assert core.endpoints('teps2') == {}
