"""Single source of the package version (read by setuptools via AST at
build time, so it must stay a plain literal with no imports)."""

__version__ = '0.1.0'
