"""The single registry of every ``XSKY_*`` environment variable.

Contract (enforced by the ``env-registry`` xskylint rule): any
``XSKY_*`` name the tree mentions as a string literal must be declared
here with its effective default and a one-line doc, and
``docs/reference/environment.md`` must exactly match
:func:`render_markdown` — regenerate it with::

    python -m skypilot_tpu.utils.env_registry > docs/reference/environment.md

Why a registry instead of grepping: at introduction, 100 distinct
``XSKY_*`` reads existed in the tree and only 45 appeared anywhere in
docs/ — unenforced config surface rots fastest. Keeping the table as
data (not prose) makes the docs generable and the drift checkable.

This module is DEPENDENCY-FREE by design: the lint engine executes it
standalone (no package import), so it must never import anything from
``skypilot_tpu``.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional

UNSET = None   # rendered as "(unset)"


@dataclasses.dataclass(frozen=True)
class EnvVar:
    name: str
    default: Optional[str]   # effective default, as the user would set it
    doc: str                 # one line; starts capitalized, no period needed


_VARS = [
    # ---- client / API server ----------------------------------------------
    EnvVar('XSKY_API_SERVER', UNSET,
           'API-server URL for remote mode (overrides config '
           'api_server.endpoint; unset = local execution)'),
    EnvVar('XSKY_API_TOKEN', UNSET,
           'Bearer token sent by the remote client when the server '
           'requires auth'),
    EnvVar('XSKY_AUTH', '',
           'Auth token the TPU tunnel proxy forwards to the API server'),
    EnvVar('XSKY_REQUIRE_AUTH', '0',
           'Set to 1 to make the API server reject unauthenticated '
           'requests'),
    EnvVar('XSKY_TUNNEL_ALLOW_ANY', '0',
           'Set to 1 to let the tunnel endpoint accept any client '
           '(dev only)'),
    EnvVar('XSKY_CONFIG', '~/.xsky/config.yaml',
           'Path of the user config file'),
    EnvVar('XSKY_SERVER_CONFIG', '/etc/xsky/config.yaml',
           'Path of the API-server config file'),
    EnvVar('XSKY_WORKSPACE', 'default',
           'Active workspace name (multi-tenant cluster namespace)'),
    EnvVar('XSKY_LONG_WORKERS', '8',
           'Concurrent long-request workers in the API-server executor'),
    EnvVar('XSKY_LONG_REQUEST_TIMEOUT_S', '0',
           'Hard timeout for long requests (0 disables)'),
    EnvVar('XSKY_WATCHDOG_INTERVAL_S', '2',
           'Executor watchdog tick: in-flight request lease renewal '
           'cadence'),
    EnvVar('XSKY_SERVER_DB', '~/.xsky/server/requests.db',
           'Path of the API-server requests database'),
    EnvVar('XSKY_REQUEST_RETENTION_HOURS', '72',
           'Finished requests older than this are garbage-collected '
           '(<=0 disables)'),
    EnvVar('XSKY_REQUEST_RECONCILE_GRACE_S', '5',
           'Reconciler grace before a leaseless in-flight request '
           'counts as stranded'),
    EnvVar('XSKY_RECONCILE_INTERVAL_S', '60',
           'Background reconciler tick interval'),
    # ---- OAuth / users -----------------------------------------------------
    EnvVar('XSKY_OAUTH_ISSUER', '',
           'OIDC issuer URL; empty disables OAuth login'),
    EnvVar('XSKY_OAUTH_CLIENT_ID', '',
           'OAuth client id for the authorization-code flow'),
    EnvVar('XSKY_OAUTH_CLIENT_SECRET', UNSET,
           'OAuth client secret (confidential clients)'),
    EnvVar('XSKY_OAUTH_SCOPE', 'openid profile email',
           'Scopes requested during OAuth login'),
    EnvVar('XSKY_OAUTH_USERINFO_TTL_S', '300',
           'How long validated userinfo responses are cached'),
    EnvVar('XSKY_USER_HASH', UNSET,
           'Force the local user hash (multi-user test isolation)'),
    # ---- state layer -------------------------------------------------------
    EnvVar('XSKY_STATE_DB', '~/.xsky/state.db',
           'Path of the shared control-plane state database'),
    EnvVar('XSKY_DB_URL', UNSET,
           'postgres:// URL routing the state layer to postgres '
           '(multi-replica API servers); unset = sqlite'),
    EnvVar('XSKY_SQLITE_SYNC', 'NORMAL',
           'PRAGMA synchronous for WAL connections (FULL restores '
           'per-commit fsync, ~29 ms each on overlayfs)'),
    EnvVar('XSKY_STATE_READ_POOL', '1',
           'Per-thread WAL read pool for state reads; 0 restores '
           'reads-under-the-write-lock (bench comparisons)'),
    EnvVar('XSKY_STATE_READ_WORKERS', '1',
           'Width of the read gate: concurrent row-materializing '
           'readers (raise on hosts with real core counts)'),
    EnvVar('XSKY_JOURNAL_FLUSH_S', '0',
           'Journal write-coalescing window; 0 commits per event'),
    EnvVar('XSKY_LEASE_TTL_S', '60',
           'Liveness-lease TTL: a holder silent this long counts as '
           'dead to the reconciler'),
    EnvVar('XSKY_SERVER_ID', UNSET,
           'Stable identity of this API-server process in the '
           'ownership hash ring (unset = host:pid)'),
    EnvVar('XSKY_DB_LOCK_RETRY_S', '5.0',
           "Total backoff budget absorbing 'database is locked' "
           'races on the shared requests DB (multi-server mode)'),
    # ---- resilience / chaos / tracing / metrics ---------------------------
    EnvVar('XSKY_CHAOS_PLAN', UNSET,
           'Fault-injection plan: inline JSON or a path to one '
           '(unset = chaos disabled, zero overhead)'),
    EnvVar('XSKY_TRACING', '1',
           'Set to 0 to disable request-scoped tracing (span() '
           'returns a no-op singleton)'),
    EnvVar('XSKY_TRACE_CONTEXT', UNSET,
           'Internal: <trace_id>:<span_id> handoff to controller/'
           'worker subprocesses (set by env_for_child)'),
    EnvVar('XSKY_TIMELINE_FILE', UNSET,
           'Path enabling the Chrome-trace timeline recorder'),
    # ---- metrics history ---------------------------------------------------
    EnvVar('XSKY_METRICS_RECORD_INTERVAL_S', '15',
           'Metrics-history recorder tick: how often the /metrics '
           'exposition is sampled into metric_points'),
    EnvVar('XSKY_METRICS_RAW_RETENTION_S', '7200',
           'Raw-tier retention of recorded metric points (one point '
           'per series per tick)'),
    EnvVar('XSKY_METRICS_1M_RETENTION_S', '86400',
           'Retention of the per-minute avg/min/max rollup tier'),
    EnvVar('XSKY_METRICS_10M_RETENTION_S', '604800',
           'Retention of the per-10-minute rollup tier'),
    EnvVar('XSKY_METRICS_MAX_SERIES', '20000',
           'Cardinality clamp per recorder tick: series beyond this '
           'are dropped (keep-first, stable name order)'),
    EnvVar('XSKY_METRICS_ANOMALY_FACTOR', '2',
           'Step-time-regression detector: recent p50 past this '
           'multiple of the trailing baseline journals an anomaly'),
    EnvVar('XSKY_METRICS_ANOMALY_MIN_POINTS', '4',
           'Recorder samples a detector needs before it may fire '
           '(and the recent-window width, in samples)'),
    EnvVar('XSKY_DEBUG', '0',
           'Set to 1 for debug-level logging'),
    EnvVar('XSKY_MINIMIZE_LOGGING', '0',
           'Set to 1 to reduce CLI log output to warnings'),
    EnvVar('XSKY_DISABLE_USAGE_COLLECTION', '0',
           'Set to 1 to disable anonymous usage reporting'),
    EnvVar('XSKY_USAGE_ENDPOINT', UNSET,
           'Override the usage-reporting endpoint'),
    # ---- static analysis (xsky lint) ---------------------------------------
    EnvVar('XSKY_LINT_CACHE', '1',
           'Set to 0 to disable the mtime+size-keyed AST cache the '
           'lint CLI keeps under .xskylint_cache/ (same as '
           '--no-cache)'),
    EnvVar('XSKY_LINT_CACHE_DIR', UNSET,
           'Override the AST-cache directory (default: '
           '<repo root>/.xskylint_cache)'),
    # ---- catalog -----------------------------------------------------------
    EnvVar('XSKY_CATALOG_URL_BASE', UNSET,
           'Base URL of a hosted catalog; set to enable hosted-'
           'catalog refresh'),
    EnvVar('XSKY_CATALOG_CACHE_DIR', '~/.xsky/catalogs',
           'Local cache directory for hosted catalogs'),
    EnvVar('XSKY_CATALOG_REFRESH_HOURS', '7',
           'Re-download a hosted catalog after this age'),
    EnvVar('XSKY_CATALOG_SCHEMA_VERSION', 'v1',
           'Pinnable hosted-catalog schema directory'),
    # ---- clouds / provisioning --------------------------------------------
    EnvVar('XSKY_ENABLE_FAKE_CLOUD', '0',
           'Set to 1 to enable the fake cloud (tests, benches, '
           'chaos drills)'),
    EnvVar('XSKY_FAKE_CLOUD_DIR', '~/.xsky/fake_cloud',
           'Backing directory of fake-cloud instance state'),
    EnvVar('XSKY_ENABLE_DOCKER_CLOUD', '0',
           'Set to 1 to enable the local-docker cloud'),
    EnvVar('XSKY_SSH_NODE_POOLS', '~/.xsky/ssh_node_pools.yaml',
           'Path of the ssh-cloud node-pool inventory'),
    EnvVar('XSKY_SSH_ALLOCATIONS', '~/.xsky/ssh_allocations.json',
           'Path of the ssh-cloud allocation ledger'),
    EnvVar('XSKY_STORE_TRANSPORT', UNSET,
           "Set to 'cli' to force CLI-based object-store transfers "
           'over the REST client'),
    EnvVar('XSKY_LOCAL_STORE_DIR', '~/.xsky/local_store',
           'Backing directory of the local object store'),
    EnvVar('XSKY_WHEEL_DIR', '~/.xsky/wheels',
           'Cache directory for the bootstrap wheel synced to '
           'cluster hosts'),
    EnvVar('XSKY_BOOTSTRAP_LOCAL', '0',
           'Set to 1 to build the bootstrap wheel from the local '
           'tree instead of the cache'),
    # ---- backend / gang execution -----------------------------------------
    EnvVar('XSKY_CLUSTER_ROOT', '~/.xsky',
           'Agent-side runtime root on cluster hosts (jobs.db, logs, '
           'spools live under it)'),
    EnvVar('XSKY_FANOUT_WORKERS', '16',
           'Thread-pool width of per-host fan-out '
           '(parallelism.run_in_parallel)'),
    EnvVar('XSKY_NODE_IPS', UNSET,
           'Set by the gang launcher: newline-separated node IPs of '
           'the slice'),
    EnvVar('XSKY_NODE_RANK', UNSET,
           'Set by the gang launcher: this host\'s node rank'),
    EnvVar('XSKY_NUM_NODES', UNSET,
           'Set by the gang launcher: node count of the slice'),
    EnvVar('XSKY_NUM_HOSTS', '1',
           'Host count the workload process sees (multi-host '
           'detection in parallel/distributed.py)'),
    EnvVar('XSKY_HOST_RANK', '0',
           'Set by the gang launcher: this host\'s rank; keys the '
           'telemetry spool'),
    EnvVar('XSKY_COORDINATOR_ADDRESS', UNSET,
           'Set by the gang launcher: jax.distributed coordinator '
           'host:port'),
    EnvVar('XSKY_JOB_ID', UNSET,
           'Set by the job runner: the cluster job id of the '
           'workload process'),
    EnvVar('XSKY_AGENT_NO_SELF_TEARDOWN', UNSET,
           'Set to any value to disable agent-side idle '
           'self-teardown'),
    # ---- async checkpoint plane (agent/checkpointd.py) ---------------------
    EnvVar('XSKY_CKPT', '1',
           'Set to 0 to disable the async multi-tier checkpoint '
           'plane entirely'),
    EnvVar('XSKY_CKPT_DIR', UNSET,
           'Local-tier checkpoint directory (set per rank by the '
           'gang launcher; unset = plane inactive)'),
    EnvVar('XSKY_CKPT_PEER_DIRS', UNSET,
           'Newline-separated peer-tier directories (the K next '
           'hosts\' roots; set by the gang launcher)'),
    EnvVar('XSKY_CKPT_REPLICAS', '1',
           'Gang peers each rank replicates its newest shard to'),
    EnvVar('XSKY_CKPT_MIN_INTERVAL_S', '15',
           'Floor of the auto-tuned checkpoint cadence'),
    EnvVar('XSKY_CKPT_MAX_INTERVAL_S', '600',
           'Ceiling of the auto-tuned checkpoint cadence'),
    EnvVar('XSKY_CKPT_MTTF_S', UNSET,
           'MTTF hint the cadence plans against (threaded by the '
           'jobs controller from the recovery journal; unset = '
           'pessimistic 1800 s default)'),
    EnvVar('XSKY_CKPT_SCOPE', UNSET,
           'Journal scope checkpoint restores account under (the '
           'jobs controller threads job/<id>)'),
    EnvVar('XSKY_CKPT_KEEP', '2',
           'Snapshots kept per checkpoint directory (older copies '
           'are the torn-write fallback)'),
    # ---- managed jobs ------------------------------------------------------
    EnvVar('XSKY_JOBS_DB', '~/.xsky/managed_jobs.db',
           'Path of the managed-jobs database'),
    EnvVar('XSKY_JOBS_LOG_DIR', '~/.xsky/jobs_logs',
           'Directory of managed-job controller logs'),
    EnvVar('XSKY_JOBS_POLL_INTERVAL', '2.0',
           'Jobs-controller status-probe interval'),
    EnvVar('XSKY_JOBS_MAX_LAUNCHING', 'min(8, cpus)',
           'Concurrent managed-job launches (default derives from '
           'host cpu count)'),
    EnvVar('XSKY_JOBS_MAX_PARALLEL', 'mem-derived',
           'Alive managed-job controllers (default derives from '
           'host memory)'),
    EnvVar('XSKY_JOBS_MAX_CONTROLLER_RESPAWNS', '3',
           'Dead-controller respawn budget before a job is failed'),
    EnvVar('XSKY_JOBS_CONTROLLER_REMOTE', UNSET,
           'Run the managed-jobs controller on a controller cluster '
           '(set by the relay; empty string = forced local)'),
    # ---- fleet scheduler / elastic gangs -----------------------------------
    EnvVar('XSKY_FLEET_ELASTIC', '1',
           'Set to 0 to disable elastic gang shrink/grow-back (every '
           'lost rank then costs a full relaunch)'),
    EnvVar('XSKY_FLEET_SHARES', UNSET,
           "Weighted fair shares per workspace ('prod=4,research=2'; "
           'unlisted workspaces weigh 1)'),
    EnvVar('XSKY_FLEET_AGING_S', '300',
           'Starvation aging: seconds of queue wait worth one '
           'admission-priority point'),
    EnvVar('XSKY_FLEET_SHARE_PENALTY', '1.0',
           'Admission-score penalty per running-job-over-weight of '
           'the workspace (fair-share strength)'),
    EnvVar('XSKY_FLEET_DECAY_S', '1800',
           'Placement-pressure half-life: journalled preemptions/'
           'capacity errors decay by half each window'),
    EnvVar('XSKY_FLEET_BLOCK_THRESHOLD', '1.0',
           'Decayed pressure at/above which a placement is avoided '
           '(launch blocklist, spot placer, grow-back gate)'),
    EnvVar('XSKY_FLEET_GROWBACK_S', '60',
           'Seconds a shrunk gang waits before each grow-back probe'),
    EnvVar('XSKY_FLEET_MIN_SURVIVORS', '0.5',
           'Smallest surviving fraction of the full gang worth '
           'running shrunk (below it: full relaunch)'),
    EnvVar('XSKY_ELASTIC_GENERATION', UNSET,
           'Set by the jobs controller on every gang (re)submit: the '
           'incarnation counter workloads and chaos plans key on'),
    # ---- serve -------------------------------------------------------------
    EnvVar('XSKY_SERVE_DB', '~/.xsky/serve.db',
           'Path of the serve-plane database'),
    EnvVar('XSKY_SERVE_LOG_DIR', '~/.xsky/serve',
           'Directory of serve controller/replica logs'),
    EnvVar('XSKY_SERVE_INTERVAL', '2.0',
           'Serve-controller tick interval (probe + autoscale)'),
    EnvVar('XSKY_SERVE_PROBE_RETRIES', '1',
           'Transient readiness-probe failures absorbed before '
           'NOT_READY'),
    EnvVar('XSKY_SERVE_PROBE_TIMEOUT', '5',
           'Readiness-probe HTTP timeout'),
    EnvVar('XSKY_SERVE_MAX_CONTROLLER_RESPAWNS', '3',
           'Dead-serve-controller respawn budget before FAILED'),
    EnvVar('XSKY_SERVE_CONTROLLER_REMOTE', UNSET,
           'Run the serve controller on a controller cluster (set by '
           'the relay; empty string = forced local)'),
    # ---- serving SLO plane -------------------------------------------------
    EnvVar('XSKY_LB_RECORDS', '1',
           'Per-request lifecycle records at the load balancer; 0 '
           'disables record-keeping (bench baseline, no SLO signal)'),
    EnvVar('XSKY_LB_RING_SIZE', '2048',
           'LB request-record ring capacity; size to expected QPS x '
           'longest burn window'),
    EnvVar('XSKY_SLO_SCRAPE_INTERVAL_S', '15',
           'SLO monitor cadence: replica /metrics scrape + burn-rate '
           'evaluation per service'),
    EnvVar('XSKY_SLO_SCRAPE_TIMEOUT', '5',
           'Replica /metrics scrape HTTP timeout'),
    EnvVar('XSKY_SLO_BURN_WINDOWS', '300,3600',
           'Burn-rate windows in seconds, comma-separated (breach '
           'requires every window over threshold)'),
    EnvVar('XSKY_SLO_BURN_THRESHOLD', '1.0',
           'Burn rate at/above which an objective breaches (1.0 = '
           'budget spent exactly as fast as it accrues)'),
    EnvVar('XSKY_SLO_EXEMPLAR_TOP_K', '8',
           'Slow-request waterfall exemplars persisted per SLO '
           'evaluation (0 disables the exemplar table writes)'),
    EnvVar('XSKY_ANATOMY', '1',
           'Per-request anatomy recorder on replicas (phase '
           'accumulators + sealed ring records); 0 disables — the '
           'bench_decode overhead rung\'s baseline arm'),
    EnvVar('XSKY_ANATOMY_RING_SIZE', '2048',
           'Replica anatomy-record ring capacity; size to expected '
           'per-replica QPS x scrape interval'),
    # ---- closed-loop serving control ---------------------------------------
    EnvVar('XSKY_REMEDIATION_ENABLED', '1',
           'Set to 0 to disable the anomaly→remediation engine '
           '(detectors still journal; no actions fire)'),
    EnvVar('XSKY_REMEDIATION_COOLDOWN_S', '120',
           'Flap-suppression window: an anomaly re-firing within this '
           'of its last applied action is deduped, not re-actioned'),
    EnvVar('XSKY_DRAIN_DEADLINE_S', '30',
           'Graceful replica drain deadline: inflight requests get '
           'this long to finish before forced termination'),
    EnvVar('XSKY_DRAIN_ON_PREEMPTION', '1',
           'Set to 0 to disable the pre-emptive peer drain when a '
           'spot preemption reclaims a shared placement'),
    EnvVar('XSKY_LB_RETRY_AFTER_S', '2',
           'Retry-After hint on the 503 shed when every routable '
           'replica is draining'),
    # ---- workload telemetry ------------------------------------------------
    EnvVar('XSKY_TELEMETRY', '1',
           'Set to 0 to disable workload telemetry emission entirely'),
    EnvVar('XSKY_TELEMETRY_DIR', UNSET,
           'Telemetry spool directory (set by the gang launcher; '
           'unset = emit() is a no-op)'),
    EnvVar('XSKY_TELEMETRY_INTERVAL_S', '2',
           'Spool write interval (never per step: per-step writes '
           'measured 8x loop cost)'),
    EnvVar('XSKY_TELEMETRY_HB_STALE_S', '30',
           'Heartbeat staleness after which a rank is DEAD'),
    EnvVar('XSKY_TELEMETRY_PROGRESS_STALE_S', '300',
           'Progress staleness after which a live-heartbeat rank is '
           'HUNG'),
    EnvVar('XSKY_TELEMETRY_PULL_INTERVAL_S', '10',
           'Control-plane spool-pull rate limit'),
    # ---- training flight recorder ------------------------------------------
    EnvVar('XSKY_FLIGHTREC', '1',
           'Set to 0 to disable the training flight recorder (per-step '
           'anatomy ring + black-box dumps)'),
    EnvVar('XSKY_FLIGHTREC_RING_SIZE', '512',
           'Sealed step records kept in the per-rank ring'),
    EnvVar('XSKY_FLIGHTREC_DIR', UNSET,
           'Black-box dump directory (crash/SIGTERM/stall-verdict '
           'arms; unset = no dumps)'),
    EnvVar('XSKY_FLIGHTREC_TAIL', '8',
           'Newest sealed records riding each telemetry sample as its '
           'flightrec key'),
    EnvVar('XSKY_FLIGHTREC_PUSH_INTERVAL_S', '2',
           'Minimum interval between flightrec ride-along pushes onto '
           'the telemetry sample'),
    # ---- goodput attribution ledger ---------------------------------------
    EnvVar('XSKY_GOODPUT_RECORD_INTERVAL_S', '30',
           'Jobs-controller cadence for folding + persisting the '
           'goodput attribution ledger'),
    EnvVar('XSKY_GOODPUT_HISTORY_ROWS', '20000',
           'Telemetry-history rows one ledger fold consumes (the '
           'table retention bound)'),
    EnvVar('XSKY_GOODPUT_INCARNATION_GAP_S', '2',
           'started_ts jump that splits telemetry history into '
           'elastic incarnations'),
    # ---- device profiling --------------------------------------------------
    EnvVar('XSKY_PROFILE', '1',
           'Set to 0 to disable the always-on step-anatomy sampler'),
    EnvVar('XSKY_PROFILE_SAMPLE_EVERY', '16',
           'Sample every Nth step with a block_until_ready probe'),
    EnvVar('XSKY_PROFILE_WARMUP_STEPS', '8',
           'Compiles within the first N steps are warmup, not a '
           'recompile storm'),
    EnvVar('XSKY_PROFILE_STALE_S', '600',
           'Profile summary lagging its rank\'s heartbeat by this '
           'much is verdicted stale'),
    EnvVar('XSKY_PROFILE_HOSTBOUND_RATIO', '0.5',
           'dispatch/(dispatch+device) above this ⇒ host-bound '
           'verdict'),
    EnvVar('XSKY_PROFILE_RECOMPILE_N', '3',
           'Post-warmup compiles at or above this ⇒ recompile-storm '
           'verdict'),
    EnvVar('XSKY_PROFILE_HBM_PRESSURE', '0.92',
           'HBM peak/limit at or above this ⇒ hbm-pressure verdict'),
    EnvVar('XSKY_PROFILER_FAKE', '0',
           'Set to 1 for the fake profiler seam (no jax import; '
           'fake-cloud drills)'),
    EnvVar('XSKY_PROFILER_FAKE_DISPATCH_S', '0.001',
           'Fake profiler: synthetic per-step host dispatch gap'),
    EnvVar('XSKY_PROFILER_FAKE_DEVICE_S', '0.004',
           'Fake profiler: synthetic per-step device time'),
    EnvVar('XSKY_PROFILER_FAKE_HBM_USE', '2147483648',
           'Fake profiler: synthetic HBM bytes in use (2 GiB)'),
    EnvVar('XSKY_PROFILER_FAKE_HBM_LIMIT', '17179869184',
           'Fake profiler: synthetic HBM byte limit (16 GiB)'),
    # ---- compute path ------------------------------------------------------
    EnvVar('XSKY_DECODE_ATTN', UNSET,
           "Set to 'xla' to route decode attention through XLA "
           'instead of the Pallas kernel'),
    EnvVar('XSKY_DECODE_BLOCK_KV', '256',
           'KV block size of the Pallas decode-attention kernel'),
    EnvVar('XSKY_DECODE_FAST_TICK', '1',
           "Set to '0' to pin the legacy decode tick (host-side "
           'finish scan, per-tick sampling-param rebuild) instead of '
           'the fused masked fast path'),
    EnvVar('XSKY_FLASH_BLOCK_Q', '512',
           'Q block size of the Pallas flash-attention kernel'),
    EnvVar('XSKY_FLASH_BLOCK_KV', '512',
           'KV block size of the Pallas flash-attention kernel'),
    EnvVar('XSKY_NATIVE_CACHE', '~/.xsky/native',
           'Cache directory of the native data-loader extension'),
]

REGISTRY: Dict[str, EnvVar] = {v.name: v for v in _VARS}
assert len(REGISTRY) == len(_VARS), 'duplicate env var declaration'


def declared_names() -> set:
    return set(REGISTRY)


def render_markdown() -> str:
    """docs/reference/environment.md, exactly. The env-registry lint
    diffs the committed file against this rendering."""
    lines = [
        '# Environment variables',
        '',
        '<!-- GENERATED FILE — do not edit by hand. Regenerate with:',
        '     python -m skypilot_tpu.utils.env_registry '
        '> docs/reference/environment.md -->',
        '',
        'Every `XSKY_*` variable the tree reads, generated from',
        '`skypilot_tpu/utils/env_registry.py` (the authoritative',
        'registry — the `env-registry` lint in',
        '[static analysis](../static-analysis.md) rejects reads of',
        'undeclared variables and a stale copy of this page).',
        '',
        '| Variable | Default | What it does |',
        '|---|---|---|',
    ]
    for name in sorted(REGISTRY):
        var = REGISTRY[name]
        if var.default is None:
            default = '(unset)'
        elif var.default == '':
            default = '(empty)'
        else:
            default = f'`{var.default}`'
        lines.append(f'| `{name}` | {default} | {var.doc} |')
    lines.append('')
    return '\n'.join(lines)


def main() -> int:
    print(render_markdown(), end='')
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
