#!/usr/bin/env python3
"""Fake-cloud launch fan-out micro-benchmark.

Launches an N-host cluster end-to-end on the fake cloud twice — once
with `XSKY_FANOUT_WORKERS=1` (the pre-fan-out sequential control
plane) and once at the configured width — with a per-host bring-up
latency injected at the `fanout.worker` chaos point, and prints ONE
JSON line comparing launch wall-clock:

    {"metric": "launch_wall_clock_s", "hosts": 16,
     "sequential_s": ..., "parallel_s": ..., "speedup": ..., ...}

Each launch exercises every converted fan-out phase (volume mount,
workdir sync, file-mount sync, task setup) across all hosts, so the
sequential run pays `hosts × phases × latency` and the parallel run
`ceil(hosts/workers) × phases × latency`. The parallel run is traced
via `XSKY_TIMELINE_FILE`; the tool verifies per-host bring-up events
actually overlap in time and reports the peak concurrency it saw.

A second mode, ``--trace-overhead``, measures the tracing subsystem's
cost instead: two identical parallel launches — one with
``XSKY_TRACING=0`` (spans compiled out to the no-op singleton) and one
with tracing enabled (every phase/rank span persisted to the state DB)
— and asserts the traced launch costs <2% extra wall-clock (exit 1
otherwise). This is the acceptance gate that keeps span recording off
the launch critical path.

Usage:
    python tools/bench_fanout.py [--hosts 16] [--latency 0.2]
                                 [--workers 16] [--keep-trace PATH]
                                 [--trace-overhead]
"""
import argparse
import json
import os
import sys
import tempfile
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

# v5e packs 8 chips per host: chips = hosts * 8 resolves to an N-host
# slice in the topology database.
_CHIPS_PER_HOST = 8


def _setup_env(workdir: str, latency_s: float) -> None:
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    os.environ['XSKY_ENABLE_FAKE_CLOUD'] = '1'
    os.environ['XSKY_STATE_DB'] = os.path.join(workdir, 'state.db')
    os.environ['XSKY_FAKE_CLOUD_DIR'] = os.path.join(workdir,
                                                     'fake_cloud')
    os.environ['XSKY_CHAOS_PLAN'] = json.dumps({
        'points': {'fanout.worker': {'latency_s': latency_s}}})


def _make_task(hosts: int, scratch: str):
    from skypilot_tpu import Resources, Task
    src_dir = os.path.join(scratch, 'workdir')
    os.makedirs(src_dir, exist_ok=True)
    with open(os.path.join(src_dir, 'payload.txt'), 'w',
              encoding='utf-8') as f:
        f.write('bench')
    mount_src = os.path.join(scratch, 'mount_src.txt')
    with open(mount_src, 'w', encoding='utf-8') as f:
        f.write('mounted')
    # run=None: the metric is bring-up (provision → mounts → sync →
    # setup) wall-clock; job submission/execution is not part of it.
    task = Task('bench-fanout', run=None, setup='true',
                workdir=src_dir,
                file_mounts={'bench/in.txt': mount_src})
    task.set_resources(Resources(
        accelerators=f'tpu-v5e-{hosts * _CHIPS_PER_HOST}',
        volumes=[{'name': 'benchvol',
                  'path': os.path.join(scratch, 'vol')}]))
    return task


def _one_launch(name: str, hosts: int, workers: int, scratch: str,
                trace_path: str) -> float:
    from skypilot_tpu import core
    from skypilot_tpu import execution
    from skypilot_tpu.utils import timeline
    os.environ['XSKY_FANOUT_WORKERS'] = str(workers)
    os.environ['XSKY_TIMELINE_FILE'] = trace_path
    timeline.reset_for_test()
    task = _make_task(hosts, scratch)
    t0 = time.monotonic()
    execution.launch(task, cluster_name=name, detach_run=True)
    elapsed = time.monotonic() - t0
    timeline.save(trace_path)
    core.down(name)
    return elapsed


def _fanout_concurrency(trace_path: str) -> int:
    """Peak number of overlapping fanout.* events in a Chrome trace."""
    with open(trace_path, encoding='utf-8') as f:
        events = json.load(f)['traceEvents']
    deltas = []
    for e in events:
        if not e['name'].startswith('fanout.'):
            continue
        if e['ph'] == 'B':
            deltas.append((e['ts'], 1))
        elif e['ph'] == 'E':
            deltas.append((e['ts'], -1))
    cur = peak = 0
    # Close ('-1') before open at equal timestamps: undercounts rather
    # than fabricating overlap.
    for _, d in sorted(deltas):
        cur += d
        peak = max(peak, cur)
    return peak


def _trace_overhead(args, scratch: str) -> int:
    """Tracing-overhead mode: identical parallel launches with spans
    disabled vs enabled; asserts <2% wall-clock cost."""
    max_overhead_pct = 2.0
    repeats = 3
    # Untimed warm-up launch: first-launch one-time costs (state-DB
    # creation, fake-cloud store init, lazy imports) would otherwise
    # be charged to whichever measured run goes first and drown the
    # few-ms effect being measured.
    os.environ['XSKY_TRACING'] = '0'
    _one_launch('bench-overhead-warmup', args.hosts, args.workers,
                scratch, os.path.join(scratch, 'trace_warmup.json'))
    # Interleaved best-of-N: fake-cloud launch wall-clock jitters far
    # more run-to-run (subprocess spawns, agent polls) than the
    # few-ms effect under test; min-of-N per mode suppresses it.
    base_runs, traced_runs = [], []
    for i in range(repeats):
        os.environ['XSKY_TRACING'] = '0'
        base_runs.append(_one_launch(
            f'bench-overhead-base-{i}', args.hosts, args.workers,
            scratch, os.path.join(scratch, f'trace_base_{i}.json')))
        os.environ['XSKY_TRACING'] = '1'
        traced_runs.append(_one_launch(
            f'bench-overhead-traced-{i}', args.hosts, args.workers,
            scratch, os.path.join(scratch, f'trace_traced_{i}.json')))
    base_s, traced_s = min(base_runs), min(traced_runs)
    overhead_pct = (traced_s - base_s) / base_s * 100.0
    from skypilot_tpu import state
    spans = len(state.get_spans(
        (state.find_trace_ids('bench-overhead-traced-0') or [''])[0]))
    ok = overhead_pct < max_overhead_pct
    print(json.dumps({
        'metric': 'tracing_overhead',
        'hosts': args.hosts,
        'workers': args.workers,
        'injected_latency_s': args.latency,
        'untraced_s': round(base_s, 3),
        'traced_s': round(traced_s, 3),
        'untraced_runs_s': [round(s, 3) for s in base_runs],
        'traced_runs_s': [round(s, 3) for s in traced_runs],
        'overhead_pct': round(overhead_pct, 2),
        'spans_recorded': spans,
        'max_overhead_pct': max_overhead_pct,
        'pass': ok,
    }))
    return 0 if ok else 1


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--hosts', type=int, default=16,
                        choices=[1, 4, 8, 16, 32],
                        help='fake-cloud v5e slice sizes (hosts)')
    parser.add_argument('--latency', type=float, default=0.2,
                        help='injected per-host bring-up latency (s)')
    parser.add_argument('--workers', type=int, default=16,
                        help='fan-out width for the parallel run')
    parser.add_argument('--keep-trace', default=None,
                        help='copy the parallel run trace here')
    parser.add_argument('--trace-overhead', action='store_true',
                        help='measure span-recording cost: parallel '
                             'launch with XSKY_TRACING=0 vs enabled; '
                             'exit 1 if the traced launch costs >2%% '
                             'extra wall-clock')
    args = parser.parse_args()

    scratch = tempfile.mkdtemp(prefix='xsky-bench-fanout-')
    _setup_env(scratch, args.latency)
    from skypilot_tpu import check as check_lib
    check_lib.set_enabled_clouds_for_test(['fake'])
    if args.trace_overhead:
        return _trace_overhead(args, scratch)

    seq_trace = os.path.join(scratch, 'trace_seq.json')
    par_trace = os.path.join(scratch, 'trace_par.json')
    sequential_s = _one_launch('bench-fanout-seq', args.hosts, 1,
                               scratch, seq_trace)
    parallel_s = _one_launch('bench-fanout-par', args.hosts,
                             args.workers, scratch, par_trace)
    peak = _fanout_concurrency(par_trace)
    if args.keep_trace:
        import shutil
        shutil.copy(par_trace, args.keep_trace)
        par_trace = args.keep_trace

    print(json.dumps({
        'metric': 'launch_wall_clock_s',
        'hosts': args.hosts,
        'workers': args.workers,
        'injected_latency_s': args.latency,
        'sequential_s': round(sequential_s, 3),
        'parallel_s': round(parallel_s, 3),
        'speedup': round(sequential_s / parallel_s, 2),
        'max_concurrent_fanout': peak,
        'overlapping': peak >= 2,
        'trace': par_trace,
    }))
    return 0


if __name__ == '__main__':
    sys.exit(main())
