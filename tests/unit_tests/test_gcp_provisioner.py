"""GCP provisioner tests against an in-memory fake of the GCP REST APIs.

Plays the role moto plays in the reference's failover tests
(tests/test_failover.py:34-60): scripted capacity errors, no network.
"""
from __future__ import annotations

import re
import urllib.parse
from typing import Any, Dict, Optional

import pytest

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.provision.gcp import instance as gcp_instance
from skypilot_tpu.provision.gcp import rest
from skypilot_tpu.provision.gcp import tpu_api


class FakeGcp:
    """Minimal in-memory TPU v2 + Compute v1 API."""

    def __init__(self) -> None:
        self.tpu_nodes: Dict[str, Dict[str, Any]] = {}
        self.vms: Dict[str, Dict[str, Any]] = {}
        self.queued: Dict[str, Dict[str, Any]] = {}
        self.disks: Dict[str, Dict[str, Any]] = {}
        self.firewalls: Dict[str, Dict[str, Any]] = {}
        self.networks: Dict[str, Dict[str, Any]] = {
            'default': {'name': 'default'}}
        self.templates: Dict[str, Dict[str, Any]] = {}
        self.migs: Dict[str, Dict[str, Any]] = {}
        self.resize_requests: Dict[str, Dict[str, Any]] = {}
        self.rr_states: list = []     # scripted resize-request states
        self.fail_create: Optional[rest.GcpApiError] = None
        self.qr_states: list = []     # scripted QR state sequence
        self.num_hosts = 1

    # Transport interface ---------------------------------------------------

    def request(self, method: str, url: str,
                params: Optional[Dict[str, str]] = None,
                body: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        path = urllib.parse.urlparse(url).path
        if 'tpu.googleapis.com' in url:
            return self._tpu(method, path, params or {}, body)
        return self._compute(method, path, params or {}, body)

    # TPU -------------------------------------------------------------------

    def _tpu(self, method, path, params, body):
        m = re.search(r'/nodes/([^/:]+):(\w+)$', path)
        if m:
            node = self.tpu_nodes[m.group(1)]
            node['state'] = 'READY' if m.group(2) == 'start' else 'STOPPED'
            return {'name': 'operations/op-x', 'done': True}
        m = re.search(r'/nodes/([^/]+)$', path)
        if m and method == 'GET':
            return self.tpu_nodes[m.group(1)]
        if m and method == 'DELETE':
            self.tpu_nodes.pop(m.group(1), None)
            return {'name': 'operations/op-del', 'done': True}
        if path.endswith('/nodes') and method == 'GET':
            return {'nodes': list(self.tpu_nodes.values())}
        if path.endswith('/nodes') and method == 'POST':
            if self.fail_create is not None:
                err, self.fail_create = self.fail_create, None
                raise err
            node_id = params['nodeId']
            self._make_node(node_id, body)
            return {'name': f'operations/create-{node_id}', 'done': True}
        m = re.search(r'/queuedResources/([^/]+)$', path)
        if m and method == 'GET':
            qr = self.queued[m.group(1)]
            if self.qr_states:
                qr['state'] = {'state': self.qr_states.pop(0)}
                if qr['state']['state'] == 'ACTIVE':
                    self._materialize_qr(m.group(1), qr)
            return qr
        if m and method == 'DELETE':
            qr = self.queued.pop(m.group(1), None)
            if qr is not None:
                # Real API force-delete reaps the QR's nodes too.
                cluster = (qr.get('tpu', {}).get('nodeSpec', [{}])[0]
                           .get('node', {}).get('labels', {})
                           .get('xsky-cluster'))
                if cluster:
                    self.tpu_nodes = {
                        nid: n for nid, n in self.tpu_nodes.items()
                        if n.get('labels', {}).get('xsky-cluster') !=
                        cluster}
            return {'name': 'operations/qr-del', 'done': True}
        if path.endswith('/queuedResources') and method == 'GET':
            return {'queuedResources': list(self.queued.values())}
        if path.endswith('/queuedResources') and method == 'POST':
            if self.fail_create is not None:
                err, self.fail_create = self.fail_create, None
                raise err
            self.last_qr_body = body
            qr_id = params['queuedResourceId']
            self.queued[qr_id] = dict(
                body, name=f'projects/p/locations/z/queuedResources/{qr_id}',
                state={'state': 'ACCEPTED'})
            return {'name': f'operations/qr-{qr_id}', 'done': True}
        if '/operations/' in path:
            return {'name': path.split('/v2/')[-1], 'done': True}
        raise AssertionError(f'unhandled TPU call {method} {path}')

    last_node_body: Optional[Dict[str, Any]] = None
    last_qr_body: Optional[Dict[str, Any]] = None

    def _make_node(self, node_id: str, body: Dict[str, Any]) -> None:
        self.last_node_body = body
        endpoints = []
        for h in range(self.num_hosts):
            endpoints.append({
                'ipAddress': f'10.1.0.{len(self.tpu_nodes) * 8 + h + 1}',
                'accessConfig': {
                    'externalIp': f'34.1.0.{len(self.tpu_nodes) * 8 + h + 1}'
                },
            })
        self.tpu_nodes[node_id] = {
            'name': f'projects/p/locations/z/nodes/{node_id}',
            'state': 'READY',
            'labels': dict(body.get('labels', {})),
            'networkEndpoints': endpoints,
        }

    def _materialize_qr(self, qr_id: str, qr: Dict[str, Any]) -> None:
        spec = qr['tpu']['nodeSpec'][0]
        multi = spec.get('multiNodeParams')
        labels = spec['node'].get('labels', {})
        count = multi['nodeCount'] if multi else 1
        for i in range(count):
            node_id = f'{qr_id}-{i}' if multi else spec['nodeId']
            if node_id not in self.tpu_nodes:
                self._make_node(node_id, {'labels': labels})

    # Compute ---------------------------------------------------------------

    def _compute(self, method, path, params, body):
        if path.endswith('/instances') and method == 'POST':
            if self.fail_create is not None:
                err, self.fail_create = self.fail_create, None
                raise err
            name = body['name']
            self.vms[name] = {
                'name': name,
                'status': 'RUNNING',
                'labels': dict(body.get('labels', {})),
                'networkInterfaces': [{
                    'networkIP': f'10.2.0.{len(self.vms) + 1}',
                    'accessConfigs': [{'natIP':
                                       f'35.2.0.{len(self.vms) + 1}'}],
                }],
            }
            return {'name': f'insert-{name}'}
        if path.endswith('/instances') and method == 'GET':
            flt = params.get('filter', '')
            m = re.search(r'labels\.(\S+)=(\S+)', flt)
            items = list(self.vms.values())
            if m:
                items = [i for i in items
                         if i['labels'].get(m.group(1)) == m.group(2)]
            return {'items': items}
        m = re.search(r'/instances/([^/]+)/(stop|start)$', path)
        if m:
            self.vms[m.group(1)]['status'] = (
                'TERMINATED' if m.group(2) == 'stop' else 'RUNNING')
            return {'name': f'{m.group(2)}-{m.group(1)}'}
        m = re.search(r'/instances/([^/]+)/attachDisk$', path)
        if m:
            self.vms[m.group(1)].setdefault('disks', []).append(dict(body))
            return {'name': f'attach-{m.group(1)}'}
        m = re.search(r'/instances/([^/]+)$', path)
        if m and method == 'GET':
            return self.vms[m.group(1)]
        if m and method == 'DELETE':
            self.vms.pop(m.group(1), None)
            return {'name': f'del-{m.group(1)}'}
        m = re.search(r'/disks/([^/]+)$', path)
        if m and method == 'GET':
            disk = self.disks.get(m.group(1))
            if disk is None:
                raise rest.GcpApiError(404, 'notFound', 'disk not found')
            return disk
        if m and method == 'DELETE':
            disk = self.disks.get(m.group(1))
            if disk and disk.get('users'):
                raise rest.GcpApiError(400, 'resourceInUse',
                                       'disk is attached')
            self.disks.pop(m.group(1), None)
            return {'name': f'del-disk-{m.group(1)}'}
        if path.endswith('/disks') and method == 'POST':
            self.disks[body['name']] = dict(body)
            return {'name': f'insert-disk-{body["name"]}'}
        if path.endswith('/disks') and method == 'GET':
            items = list(self.disks.values())
            for clause in params.get('filter', '').split(' AND '):
                m2 = re.search(r'labels\.(\S+)=(\S+)', clause)
                if m2:
                    items = [d for d in items
                             if d.get('labels', {}).get(m2.group(1)) ==
                             m2.group(2)]
            return {'items': items}
        m = re.search(r'/global/firewalls/([^/]+)$', path)
        if m and method == 'GET':
            fw = self.firewalls.get(m.group(1))
            if fw is None:
                raise rest.GcpApiError(404, 'notFound', 'no firewall')
            return fw
        if m and method == 'PATCH':
            if m.group(1) not in self.firewalls:
                raise rest.GcpApiError(404, 'notFound', 'no firewall')
            self.firewalls[m.group(1)] = dict(body)
            return {'name': f'patch-fw-{m.group(1)}'}
        if m and method == 'DELETE':
            if m.group(1) not in self.firewalls:
                raise rest.GcpApiError(404, 'notFound', 'no firewall')
            self.firewalls.pop(m.group(1))
            return {'name': f'del-fw-{m.group(1)}'}
        if path.endswith('/global/firewalls') and method == 'POST':
            if self.fail_create is not None:
                err, self.fail_create = self.fail_create, None
                raise err
            self.firewalls[body['name']] = dict(body)
            return {'name': f'insert-fw-{body["name"]}'}
        m = re.search(r'/global/networks/([^/]+)$', path)
        if m and method == 'GET':
            net = self.networks.get(m.group(1))
            if net is None:
                raise rest.GcpApiError(404, 'notFound', 'no network')
            return net
        if path.endswith('/global/networks') and method == 'POST':
            self.networks[body['name']] = dict(body)
            return {'name': f'insert-net-{body["name"]}'}
        m = re.search(r'/global/instanceTemplates(?:/([^/]+))?$', path)
        if m and method == 'POST':
            self.templates[body['name']] = dict(body)
            return {'name': f'insert-tpl-{body["name"]}'}
        if m and method == 'DELETE':
            if m.group(1) not in self.templates:
                raise rest.GcpApiError(404, 'notFound', 'no template')
            self.templates.pop(m.group(1))
            return {'name': 'del-tpl'}
        m = re.search(
            r'/instanceGroupManagers/([^/]+)/resizeRequests$', path)
        if m and method == 'POST':
            self.resize_requests[body['name']] = dict(
                body, state='ACCEPTED', mig=m.group(1))
            return {'name': f'insert-rr-{body["name"]}'}
        m = re.search(
            r'/instanceGroupManagers/([^/]+)/resizeRequests/([^/]+)$',
            path)
        if m and method == 'GET':
            rr = self.resize_requests.get(m.group(2))
            if rr is None:
                raise rest.GcpApiError(404, 'notFound', 'no rr')
            # Terminal states are sticky (like the real API): scripted
            # transitions only apply to in-flight requests.
            if self.rr_states and rr.get('state') not in (
                    'SUCCEEDED', 'FAILED', 'CANCELLED'):
                rr['state'] = self.rr_states.pop(0)
                if rr['state'] == 'SUCCEEDED':
                    self._materialize_mig(rr)
            return rr
        if m and method == 'DELETE':
            if self.resize_requests.pop(m.group(2), None) is None:
                raise rest.GcpApiError(404, 'notFound', 'no rr')
            return {'name': f'del-rr-{m.group(2)}'}
        m = re.search(
            r'/instanceGroupManagers/([^/]+)/listManagedInstances$', path)
        if m and method == 'POST':
            mig = self.migs.get(m.group(1), {})
            return {'managedInstances': [
                {'instance': f'.../instances/{n}'}
                for n in mig.get('instances', [])]}
        m = re.search(r'/instanceGroupManagers(?:/([^/]+))?$', path)
        if m and method == 'POST':
            self.migs[body['name']] = dict(body, instances=[])
            return {'name': f'insert-mig-{body["name"]}'}
        if m and method == 'GET':
            mig = self.migs.get(m.group(1))
            if mig is None:
                raise rest.GcpApiError(404, 'notFound', 'no mig')
            return mig
        if m and method == 'DELETE':
            mig = self.migs.pop(m.group(1), None)
            if mig is None:
                raise rest.GcpApiError(404, 'notFound', 'no mig')
            for name in mig.get('instances', []):
                self.vms.pop(name, None)
            return {'name': 'del-mig'}
        if '/operations/' in path:
            return {'status': 'DONE'}
        raise AssertionError(f'unhandled compute call {method} {path}')

    def _materialize_mig(self, rr: Dict[str, Any]) -> None:
        """A SUCCEEDED resize request stamps VMs from the MIG's
        template (labels included, like the real control plane)."""
        mig = self.migs[rr['mig']]
        template = self.templates[
            mig['instanceTemplate'].rsplit('/', 1)[-1]]
        for i in range(int(rr.get('resizeBy', 0))):
            name = f"{mig['baseInstanceName']}-{len(mig['instances'])}"
            self.vms[name] = {
                'name': name,
                'status': 'RUNNING',
                'labels': dict(
                    template['properties'].get('labels', {})),
                'networkInterfaces': [{
                    'networkIP': f'10.3.0.{len(self.vms) + 1}',
                    'accessConfigs': [{'natIP':
                                       f'35.3.0.{len(self.vms) + 1}'}],
                }],
            }
            mig['instances'].append(name)


@pytest.fixture()
def fake_gcp(monkeypatch):
    fake = FakeGcp()
    monkeypatch.setattr(gcp_instance, '_transport_factory', lambda: fake)
    yield fake


PROVIDER = {'project_id': 'p', 'zone': 'us-central2-b'}


def _tpu_config(num_hosts=1, num_slices=1, use_qr=False, count=1):
    return common.ProvisionConfig(
        provider_config=dict(PROVIDER),
        node_config={
            'tpu_vm': True,
            'tpu_accelerator_type': 'v5p-8',
            'tpu_runtime_version': 'v2-alpha-tpuv5',
            'tpu_num_slices': num_slices,
            'tpu_use_queued_resources': use_qr,
            'provision_timeout_s': 1,
            'qr_poll_interval_s': 0.01,
        },
        count=count)


def test_tpu_create_multihost(fake_gcp):
    fake_gcp.num_hosts = 4
    record = gcp_instance.run_instances('us-central2', 'us-central2-b',
                                        'c1', _tpu_config())
    assert record.created_instance_ids == ['c1-0']
    info = gcp_instance.get_cluster_info('us-central2', 'c1', PROVIDER)
    assert info.num_instances == 4
    hosts = info.sorted_instances()
    assert info.head_instance_id == 'c1-0-host0'
    assert [h.host_index for h in hosts] == [0, 1, 2, 3]
    assert all(h.slice_id == 'c1-0' for h in hosts)
    assert all(h.status == 'RUNNING' for h in hosts)
    statuses = gcp_instance.query_instances('c1', PROVIDER)
    assert set(statuses.values()) == {'RUNNING'}


def test_tpu_capacity_error_classified(fake_gcp):
    fake_gcp.fail_create = rest.GcpApiError(
        429, 'RESOURCE_EXHAUSTED', 'There is no more capacity in the zone')
    with pytest.raises(exceptions.CapacityError):
        gcp_instance.run_instances('us-central2', 'us-central2-b', 'c2',
                                   _tpu_config())


def test_tpu_quota_error_classified(fake_gcp):
    fake_gcp.fail_create = rest.GcpApiError(
        403, 'PERMISSION_DENIED', 'Quota limit TPUV5sPodPerProjectPerZone')
    with pytest.raises(exceptions.QuotaExceededError):
        gcp_instance.run_instances('us-central2', 'us-central2-b', 'c3',
                                   _tpu_config())


def test_queued_resource_multislice(fake_gcp):
    fake_gcp.num_hosts = 2
    fake_gcp.qr_states = ['ACCEPTED', 'PROVISIONING', 'ACTIVE']
    record = gcp_instance.run_instances(
        'us-central2', 'us-central2-b', 'ms',
        _tpu_config(num_slices=2, use_qr=True))
    assert sorted(record.created_instance_ids) == ['ms-0', 'ms-1']
    info = gcp_instance.get_cluster_info('us-central2', 'ms', PROVIDER)
    # 2 slices × 2 hosts.
    assert info.num_instances == 4
    slices = {h.slice_id for h in info.sorted_instances()}
    assert slices == {'ms-0', 'ms-1'}


def test_queued_resource_timeout(fake_gcp):
    fake_gcp.qr_states = ['ACCEPTED'] * 1000
    with pytest.raises(exceptions.QueuedResourceTimeoutError):
        gcp_instance.run_instances('us-central2', 'us-central2-b', 'qt',
                                   _tpu_config(use_qr=True))
    assert not fake_gcp.queued  # rolled back


def test_queued_resource_failed_is_capacity(fake_gcp):
    fake_gcp.qr_states = ['ACCEPTED', 'FAILED']
    with pytest.raises(exceptions.CapacityError):
        gcp_instance.run_instances('us-central2', 'us-central2-b', 'qf',
                                   _tpu_config(use_qr=True))


def test_vm_lifecycle(fake_gcp):
    cfg = common.ProvisionConfig(
        provider_config=dict(PROVIDER),
        node_config={'instance_type': 'n2-standard-8'}, count=2)
    record = gcp_instance.run_instances('us-central2', 'us-central2-b',
                                        'ctrl', cfg)
    assert sorted(record.created_instance_ids) == ['ctrl-0', 'ctrl-1']
    assert record.head_instance_id == 'ctrl-0'
    gcp_instance.stop_instances('ctrl', PROVIDER)
    statuses = gcp_instance.query_instances('ctrl', PROVIDER)
    assert set(statuses.values()) == {'STOPPED'}
    # resume
    record2 = gcp_instance.run_instances('us-central2', 'us-central2-b',
                                         'ctrl', cfg)
    assert sorted(record2.resumed_instance_ids) == ['ctrl-0', 'ctrl-1']
    gcp_instance.terminate_instances('ctrl', PROVIDER)
    assert gcp_instance.query_instances('ctrl', PROVIDER) == {}


def test_multihost_tpu_stop_rejected(fake_gcp):
    fake_gcp.num_hosts = 2
    gcp_instance.run_instances('us-central2', 'us-central2-b', 'pod',
                               _tpu_config())
    with pytest.raises(exceptions.NotSupportedError):
        gcp_instance.stop_instances('pod', PROVIDER)


def test_tpu_terminate_idempotent(fake_gcp):
    gcp_instance.run_instances('us-central2', 'us-central2-b', 'gone',
                               _tpu_config())
    gcp_instance.terminate_instances('gone', PROVIDER)
    gcp_instance.terminate_instances('gone', PROVIDER)  # no raise
    with pytest.raises(exceptions.ClusterDoesNotExist):
        gcp_instance.get_cluster_info('us-central2', 'gone', PROVIDER)


def test_preempted_node_deleted_and_recreated(fake_gcp):
    """Spot preemption: the dead node lingers in the TPU API; a
    relaunch must delete it and create fresh capacity instead of
    counting the corpse as a live node."""
    cfg = _tpu_config()
    gcp_instance.run_instances('us-c1', 'us-c1-a', 'tpu1', cfg)
    node_id = next(iter(fake_gcp.tpu_nodes))
    fake_gcp.tpu_nodes[node_id]['state'] = 'PREEMPTED'
    record = gcp_instance.run_instances('us-c1', 'us-c1-a', 'tpu1', cfg)
    assert record.created_instance_ids == [node_id]
    assert fake_gcp.tpu_nodes[node_id]['state'] == 'READY'


def test_query_reports_preempted_state(fake_gcp):
    gcp_instance.run_instances('us-c1', 'us-c1-a', 'tpu2', _tpu_config())
    node_id = next(iter(fake_gcp.tpu_nodes))
    fake_gcp.tpu_nodes[node_id]['state'] = 'PREEMPTED'
    statuses = gcp_instance.query_instances('tpu2', PROVIDER)
    # Dead-but-listed normalizes to None (cross-provider 'gone').
    assert statuses and all(s is None for s in statuses.values())


def test_stale_suspended_qr_deleted_and_recreated(fake_gcp):
    """Spot preemption on the queued-resources path: the SUSPENDED QR
    (and its node corpses) must be deleted so the relaunch creates a
    fresh QR instead of polling the dead one into CapacityError."""
    fake_gcp.qr_states = ['ACCEPTED', 'ACTIVE']
    cfg = _tpu_config(use_qr=True)
    gcp_instance.run_instances('us-central2', 'us-central2-b', 'sq', cfg)
    assert len(fake_gcp.queued) == 1
    qr = next(iter(fake_gcp.queued.values()))
    qr['state'] = {'state': 'SUSPENDED'}
    for node in fake_gcp.tpu_nodes.values():
        node['state'] = 'PREEMPTED'
    fake_gcp.qr_states = ['ACCEPTED', 'ACTIVE']
    record = gcp_instance.run_instances('us-central2', 'us-central2-b',
                                        'sq', cfg)
    assert record.created_instance_ids  # fresh capacity
    assert len(fake_gcp.queued) == 1    # new QR replaced the stale one
    states = {n['state'] for n in fake_gcp.tpu_nodes.values()}
    assert states == {'READY'}


# ---- reservations + DWS (VERDICT r2 #6) ------------------------------------


def test_reservation_rides_node_scheduling_config(fake_gcp):
    """accelerator_args.reservation → schedulingConfig.reservationName
    on the direct nodes.create body (depth the reference lacks for TPU,
    sky/provision/gcp/instance_utils.py:1475)."""
    cfg = _tpu_config()
    cfg.node_config['reservation'] = 'res-block-1'
    gcp_instance.run_instances('us-central2', 'us-central2-b', 'rsv', cfg)
    sched = fake_gcp.last_node_body['schedulingConfig']
    assert sched == {'reserved': True, 'reservationName': 'res-block-1'}


def test_reservation_rides_queued_resource(fake_gcp):
    fake_gcp.qr_states = ['ACCEPTED', 'ACTIVE']
    cfg = _tpu_config(use_qr=True)
    cfg.node_config['reservation'] = 'res-block-1'
    gcp_instance.run_instances('us-central2', 'us-central2-b', 'rq', cfg)
    body = fake_gcp.last_qr_body
    assert body['guaranteed'] == {'reserved': True}
    assert body['reservationName'] == 'res-block-1'


def test_dws_window_rides_queueing_policy(fake_gcp):
    """flex-start: the DWS wait window travels as
    queueingPolicy.validUntilDuration on the queued resource."""
    fake_gcp.qr_states = ['WAITING_FOR_RESOURCES', 'ACTIVE']
    cfg = _tpu_config(use_qr=True)
    cfg.node_config['provision_timeout_s'] = 3600
    cfg.node_config['qr_poll_interval_s'] = 0.01
    gcp_instance.run_instances('us-central2', 'us-central2-b', 'dws', cfg)
    body = fake_gcp.last_qr_body
    assert body['queueingPolicy'] == {'validUntilDuration': '3600s'}


def test_deploy_vars_flex_start_and_reserved():
    """clouds/gcp threading: provisioning_model → node_config knobs."""
    from skypilot_tpu import exceptions as exc
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu.clouds.gcp import GCP
    cloud = GCP()

    r = resources_lib.Resources(
        accelerators='tpu-v5p-8',
        accelerator_args={'provisioning_model': 'flex-start',
                          'provision_timeout': 7200})
    vars = cloud.make_deploy_resources_variables(r, 'c', 'us-central2',
                                                 'us-central2-b')
    assert vars['tpu_use_queued_resources'] is True
    assert vars['provision_timeout_s'] == 7200.0

    r = resources_lib.Resources(
        accelerators='tpu-v5p-8',
        accelerator_args={'provisioning_model': 'reserved',
                          'reservation': 'blk'})
    vars = cloud.make_deploy_resources_variables(r, 'c', 'us-central2',
                                                 'us-central2-b')
    assert vars['reservation'] == 'blk'
    assert vars['use_spot'] is False

    with pytest.raises(exc.InvalidRequestError):
        cloud.make_deploy_resources_variables(
            resources_lib.Resources(
                accelerators='tpu-v5p-8',
                accelerator_args={'provisioning_model': 'reserved'}),
            'c', 'us-central2', 'us-central2-b')
    with pytest.raises(exc.InvalidRequestError):
        cloud.make_deploy_resources_variables(
            resources_lib.Resources(
                accelerators='tpu-v5p-8',
                accelerator_args={'provisioning_model': 'bogus'}),
            'c', 'us-central2', 'us-central2-b')


# ---- volumes (network persistent disks) --------------------------------


VOL = {'name': 'data1', 'path': '/mnt/data', 'size': 50,
       'disk_tier': 'high', 'attach_mode': 'read_write',
       'auto_delete': True}


def _vm_volume_config(count=1, volumes=None):
    return common.ProvisionConfig(
        provider_config=dict(PROVIDER, volumes=volumes or [dict(VOL)]),
        node_config={'instance_type': 'n2-standard-8',
                     'volumes': volumes or [dict(VOL)]},
        count=count)


def test_vm_volume_created_attached_and_mounted(fake_gcp):
    gcp_instance.run_instances('us-central2', 'us-central2-b', 'vol1',
                               _vm_volume_config())
    # Disk created with the tier-mapped type and cluster label.
    disk = fake_gcp.disks['data1']
    assert disk['type'].endswith('pd-ssd')
    assert disk['labels']['xsky-cluster'] == 'vol1'
    assert disk['labels']['xsky-auto-delete'] == 'true'
    # Attached read-write to the single node.
    attached = fake_gcp.vms['vol1-0']['disks']
    assert attached[0]['deviceName'] == 'data1'
    assert attached[0]['mode'] == 'READ_WRITE'
    # Mount commands ride ClusterInfo (mkfs-if-blank + mount + perms).
    info = gcp_instance.get_cluster_info(
        'us-central2', 'vol1', dict(PROVIDER, volumes=[dict(VOL)]))
    assert len(info.mount_commands) == 1
    cmd = info.mount_commands[0]
    assert '/dev/disk/by-id/google-data1' in cmd
    assert 'mkfs.ext4' in cmd and '/mnt/data' in cmd
    # Round-trips through the serialized cluster_info.json.
    again = common.ClusterInfo.from_json(info.to_json())
    assert again.mount_commands == info.mount_commands


def test_vm_volume_idempotent_relaunch(fake_gcp):
    gcp_instance.run_instances('us-central2', 'us-central2-b', 'vol2',
                               _vm_volume_config())
    gcp_instance.run_instances('us-central2', 'us-central2-b', 'vol2',
                               _vm_volume_config())
    assert len(fake_gcp.vms['vol2-0']['disks']) == 1  # not re-attached


def test_vm_read_write_volume_rejects_multinode(fake_gcp):
    with pytest.raises(exceptions.InvalidSkyTpuConfigError):
        gcp_instance.run_instances('us-central2', 'us-central2-b',
                                   'vol3', _vm_volume_config(count=2))


def test_vm_read_only_volume_multinode_multiattach(fake_gcp):
    vol = dict(VOL, attach_mode='read_only', auto_delete=False)
    # read_only volumes must pre-exist (unwritable from this cluster,
    # so a blank one could never be formatted/populated).
    fake_gcp.disks['data1'] = {'name': 'data1', 'sizeGb': '50',
                               'labels': {}}
    gcp_instance.run_instances('us-central2', 'us-central2-b', 'vol4',
                               _vm_volume_config(count=2, volumes=[vol]))
    for vm in ('vol4-0', 'vol4-1'):
        assert fake_gcp.vms[vm]['disks'][0]['mode'] == 'READ_ONLY'
    info = gcp_instance.get_cluster_info(
        'us-central2', 'vol4', dict(PROVIDER, volumes=[vol]))
    # Read-only: no mkfs, ro mount.
    assert 'mkfs' not in info.mount_commands[0]
    assert '-o ro' in info.mount_commands[0]


def test_read_only_volume_must_preexist(fake_gcp):
    vol = dict(VOL, attach_mode='read_only')
    with pytest.raises(exceptions.InvalidSkyTpuConfigError):
        gcp_instance.run_instances('us-central2', 'us-central2-b',
                                   'vol4b',
                                   _vm_volume_config(volumes=[vol]))


def test_rw_multinode_fails_before_any_vm_created(fake_gcp):
    with pytest.raises(exceptions.InvalidSkyTpuConfigError):
        gcp_instance.run_instances('us-central2', 'us-central2-b',
                                   'vol3b', _vm_volume_config(count=2))
    assert not fake_gcp.vms  # nothing billed


def test_volume_deploy_vars_never_mutate_resources():
    """The provisioner annotates volume dicts (source paths); Resources
    must keep clean copies or a later failover .copy() explodes."""
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu.utils import registry
    r = resources_lib.Resources(
        cloud=registry.CLOUD_REGISTRY.from_str('fake'),
        accelerators='tpu-v5e-8',
        volumes=[{'name': 'v1', 'path': '/mnt/v'}])
    vars = r.cloud.make_deploy_resources_variables(
        r, 'c', 'fake-central1', None)
    vars['volumes'][0]['source'] = 'projects/p/zones/z/disks/v1'
    assert 'source' not in r.volumes[0]
    r.copy(region='fake-east1')  # must not raise


def test_auto_delete_volume_dies_with_cluster(fake_gcp):
    keep = dict(VOL, name='keepme', auto_delete=False)
    gcp_instance.run_instances(
        'us-central2', 'us-central2-b', 'vol5',
        _vm_volume_config(volumes=[dict(VOL)]))
    gcp_instance.run_instances(
        'us-central2', 'us-central2-b', 'vol6',
        _vm_volume_config(volumes=[keep]))
    gcp_instance.terminate_instances('vol5', PROVIDER)
    gcp_instance.terminate_instances('vol6', PROVIDER)
    assert 'data1' not in fake_gcp.disks      # auto_delete
    assert 'keepme' in fake_gcp.disks         # survives its cluster


def test_tpu_volume_rides_data_disks(fake_gcp):
    vol = dict(VOL, attach_mode='read_only')
    fake_gcp.disks['data1'] = {'name': 'data1', 'sizeGb': '50',
                               'labels': {}}
    cfg = _tpu_config()
    cfg.node_config['volumes'] = [vol]
    gcp_instance.run_instances('us-central2', 'us-central2-b', 'tvol',
                               cfg)
    # Disk pre-created, then referenced by full source path in the
    # node body's dataDisks (READ_ONLY_MANY for shared).
    assert 'data1' in fake_gcp.disks
    disks = fake_gcp.last_node_body['dataDisks']
    assert disks[0]['sourceDisk'].endswith(
        'projects/p/zones/us-central2-b/disks/data1')
    assert disks[0]['mode'] == 'READ_ONLY_MANY'
    # TPU dataDisks surface as google-persistent-disk-N, not the name.
    info = gcp_instance.get_cluster_info(
        'us-central2', 'tvol', dict(PROVIDER, volumes=[vol]))
    assert 'google-persistent-disk-1' in info.mount_commands[0]


def test_tpu_read_write_volume_rejects_multihost(fake_gcp):
    fake_gcp.num_hosts = 2
    cfg = _tpu_config(num_hosts=2)
    cfg.node_config['tpu_num_hosts'] = 2
    cfg.node_config['volumes'] = [dict(VOL)]
    with pytest.raises(exceptions.InvalidSkyTpuConfigError):
        gcp_instance.run_instances('us-central2', 'us-central2-b',
                                   'tvol2', cfg)


def test_resources_volumes_grammar():
    from skypilot_tpu import resources as resources_lib
    r = resources_lib.Resources(volumes=[{'name': 'v', 'path': '/mnt/v'}])
    assert r.volumes[0]['size'] == 100
    assert r.volumes[0]['attach_mode'] == 'read_write'
    assert r.to_yaml_config()['volumes'][0]['name'] == 'v'
    with pytest.raises(ValueError):
        resources_lib.Resources(volumes=[{'name': 'v', 'path': 'rel'}])
    with pytest.raises(ValueError):
        resources_lib.Resources(volumes=[{'path': '/mnt/v'}])
    with pytest.raises(ValueError):
        resources_lib.Resources(volumes=[{'name': 'v', 'path': '/m',
                                          'attach_mode': 'rw'}])


# ---- open_ports / firewall rules (VERDICT r4 #2) -------------------------


def test_open_ports_creates_scoped_firewall_rule(fake_gcp):
    gcp_instance.open_ports('c1', ['8080', '4000-4100'], PROVIDER)
    fw = fake_gcp.firewalls['xsky-c1-ports']
    assert fw['direction'] == 'INGRESS'
    assert fw['targetTags'] == ['xsky-c1']
    assert fw['allowed'] == [{'IPProtocol': 'tcp',
                              'ports': ['8080', '4000-4100']}]
    assert fw['network'] == 'global/networks/default'
    # Custom network rides provider_config.
    gcp_instance.open_ports('c2', ['80'],
                            dict(PROVIDER, network='global/networks/vpc1'))
    assert fake_gcp.firewalls['xsky-c2-ports']['network'] == \
        'global/networks/vpc1'


def test_open_ports_idempotent_and_merging(fake_gcp):
    gcp_instance.open_ports('c1', ['8080'], PROVIDER)
    # Subset: no-op (rule object unchanged).
    before = dict(fake_gcp.firewalls['xsky-c1-ports'])
    gcp_instance.open_ports('c1', ['8080'], PROVIDER)
    assert fake_gcp.firewalls['xsky-c1-ports'] == before
    # New port: merged into the existing rule, nothing dropped.
    gcp_instance.open_ports('c1', ['9090'], PROVIDER)
    assert fake_gcp.firewalls['xsky-c1-ports']['allowed'][0]['ports'] == \
        ['8080', '9090']


def test_cleanup_ports_deletes_rule(fake_gcp):
    gcp_instance.open_ports('c1', ['8080'], PROVIDER)
    gcp_instance.cleanup_ports('c1', PROVIDER)
    assert 'xsky-c1-ports' not in fake_gcp.firewalls
    # Absent rule: tolerated (torn down twice, or never opened).
    gcp_instance.cleanup_ports('c1', PROVIDER)


def test_open_ports_failure_raises_loudly(fake_gcp):
    fake_gcp.fail_create = rest.GcpApiError(
        403, 'PERMISSION_DENIED', 'compute.firewalls.create denied')
    with pytest.raises(exceptions.ProvisionError, match='Opening ports'):
        gcp_instance.open_ports('c1', ['8080'], PROVIDER)


def test_node_bodies_carry_cluster_tag(fake_gcp):
    gcp_instance.run_instances('us-central2', 'us-central2-b', 'c1',
                               _tpu_config())
    assert 'xsky-c1' in fake_gcp.last_node_body['tags']
    vm_cfg = common.ProvisionConfig(
        provider_config=dict(PROVIDER),
        node_config={'instance_type': 'n2-standard-8'}, count=1)
    gcp_instance.run_instances('us-central2', 'us-central2-b', 'cvm',
                               vm_cfg)
    from skypilot_tpu.provision.gcp import compute_api
    body = compute_api.vm_body({'instance_type': 'n2-standard-8'}, 'cvm',
                               'cvm-0', 'us-central2-b', True, 0)
    assert 'xsky-cvm' in body['tags']['items']


# ---- GPU VMs: reservations + DWS via MIG (VERDICT r4 #7) -----------------


def _gpu_config(count=1, **node_extra):
    node = {'instance_type': 'a2-highgpu-1g', 'gpu_type': 'nvidia-a100',
            'gpu_count': 1, 'provision_timeout_s': 1,
            'qr_poll_interval_s': 0.01}
    node.update(node_extra)
    return common.ProvisionConfig(provider_config=dict(PROVIDER),
                                  node_config=node, count=count)


def test_gpu_vm_reservation_affinity(fake_gcp):
    gcp_instance.run_instances('us-central2', 'us-central2-b', 'resv',
                               _gpu_config(reservation='block-a'))
    vm = fake_gcp.vms['resv-0']
    # The insert body's reservationAffinity pins the named block.
    body = gcp_instance.compute_api.vm_body(
        {'instance_type': 'a2-highgpu-1g', 'reservation': 'block-a'},
        'resv', 'resv-0', 'us-central2-b', True, 0)
    aff = body['reservationAffinity']
    assert aff['consumeReservationType'] == 'SPECIFIC_RESERVATION'
    assert aff['values'] == ['block-a']
    assert vm['status'] == 'RUNNING'


def test_gpu_dws_provisions_via_mig(fake_gcp):
    fake_gcp.rr_states = ['ACCEPTED', 'SUCCEEDED']
    record = gcp_instance.run_instances(
        'us-central2', 'us-central2-b', 'dws',
        _gpu_config(count=2, gpu_dws=True))
    assert sorted(record.created_instance_ids) == ['dws-0', 'dws-1']
    # Template + MIG + resize request all exist; instances carry the
    # cluster label so lifecycle ops find them.
    assert 'xsky-mig-dws' in fake_gcp.templates
    assert 'xsky-mig-dws' in fake_gcp.migs
    assert fake_gcp.vms['dws-0']['labels']['xsky-cluster'] == 'dws'
    statuses = gcp_instance.query_instances('dws', PROVIDER)
    assert set(statuses.values()) == {'RUNNING'}
    # Teardown reaps MIG + template + instances.
    gcp_instance.terminate_instances('dws', PROVIDER)
    assert fake_gcp.migs == {} and fake_gcp.templates == {}
    assert gcp_instance.query_instances('dws', PROVIDER) == {}


def test_gpu_dws_timeout_is_capacity_scoped(fake_gcp):
    fake_gcp.rr_states = ['ACCEPTED'] * 1000
    with pytest.raises(exceptions.QueuedResourceTimeoutError):
        gcp_instance.run_instances('us-central2', 'us-central2-b',
                                   'dwt', _gpu_config(gpu_dws=True))
    # Failed request cleans up its MIG/template so failover can retry
    # elsewhere without name collisions.
    assert fake_gcp.migs == {} and fake_gcp.templates == {}


def test_gpu_dws_failed_state_raises_capacity_error(fake_gcp):
    fake_gcp.rr_states = ['FAILED']
    with pytest.raises(exceptions.CapacityError):
        gcp_instance.run_instances('us-central2', 'us-central2-b',
                                   'dwf', _gpu_config(gpu_dws=True))
    assert fake_gcp.migs == {}


def test_gpu_capacity_model_deploy_vars():
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu.clouds import gcp as gcp_cloud
    cloud = gcp_cloud.GCP()
    res = resources_lib.Resources(
        cloud='gcp', accelerators={'nvidia-a100': 1},
        instance_type='a2-highgpu-1g',
        accelerator_args={'provisioning_model': 'flex-start',
                          'provision_timeout': 120,
                          'dws_run_duration': 3600})
    vars = cloud.make_deploy_resources_variables(
        res, 'c', 'us-central2', 'us-central2-b')
    assert vars['gpu_dws'] is True
    assert vars['provision_timeout_s'] == 120
    assert vars['dws_run_duration_s'] == 3600
    res2 = resources_lib.Resources(
        cloud='gcp', accelerators={'nvidia-a100': 1},
        instance_type='a2-highgpu-1g',
        accelerator_args={'provisioning_model': 'reserved',
                          'reservation': 'block-a'})
    vars2 = cloud.make_deploy_resources_variables(
        res2, 'c', 'us-central2', 'us-central2-b')
    assert vars2['reservation'] == 'block-a'


# ---- network bootstrap (VERDICT r4 missing #2, VPC half) -----------------


def test_missing_default_network_bootstraps_xsky_vpc(fake_gcp):
    del fake_gcp.networks['default']
    cfg = _tpu_config()
    gcp_instance.run_instances('us-central2', 'us-central2-b', 'nv1',
                               cfg)
    # VPC created with base rules; cluster + lifecycle ops routed to it.
    assert 'xsky-vpc' in fake_gcp.networks
    assert fake_gcp.networks['xsky-vpc']['autoCreateSubnetworks']
    assert 'xsky-vpc-internal' in fake_gcp.firewalls
    assert fake_gcp.firewalls['xsky-vpc-ssh']['allowed'][0]['ports'] == \
        ['22']
    assert cfg.node_config['network'] == 'global/networks/xsky-vpc'
    assert cfg.provider_config['network'] == 'global/networks/xsky-vpc'
    # open_ports lands its rule on the same network.
    gcp_instance.open_ports('nv1', ['8080'], cfg.provider_config)
    assert fake_gcp.firewalls['xsky-nv1-ports']['network'] == \
        'global/networks/xsky-vpc'


def test_existing_default_network_untouched(fake_gcp):
    cfg = _tpu_config()
    gcp_instance.run_instances('us-central2', 'us-central2-b', 'nv2',
                               cfg)
    assert 'xsky-vpc' not in fake_gcp.networks
    assert 'network' not in cfg.provider_config


def test_missing_user_named_network_fails_loudly(fake_gcp):
    cfg = _tpu_config()
    cfg.node_config['network'] = 'global/networks/my-vpc'
    with pytest.raises(exceptions.InvalidSkyTpuConfigError,
                       match='my-vpc'):
        gcp_instance.run_instances('us-central2', 'us-central2-b',
                                   'nv3', cfg)


def test_gpu_dws_scale_up_files_fresh_resize_request(fake_gcp):
    """Relaunching a DWS cluster with a larger count must file a NEW
    resize request for the gap — the old SUCCEEDED request must not
    satisfy the poll and return an under-provisioned gang
    (code-review r5)."""
    fake_gcp.rr_states = ['SUCCEEDED']
    gcp_instance.run_instances('us-central2', 'us-central2-b', 'dsc',
                               _gpu_config(count=2, gpu_dws=True))
    assert len(fake_gcp.vms) == 2
    fake_gcp.rr_states = ['SUCCEEDED']
    record = gcp_instance.run_instances(
        'us-central2', 'us-central2-b', 'dsc',
        _gpu_config(count=4, gpu_dws=True))
    assert len(fake_gcp.vms) == 4
    assert len(record.created_instance_ids) == 2
    # Two distinct requests were filed (named by their FROM size).
    assert {'xsky-mig-dsc-rr0', 'xsky-mig-dsc-rr2'} <= set(
        fake_gcp.resize_requests)


def test_gpu_dws_refiles_after_run_duration_reclaim(fake_gcp):
    """DWS run-duration expiry reclaims the VMs but leaves the MIG and
    its SUCCEEDED resize request: relaunch must delete the stale
    request and file a fresh one — never report success with zero
    instances (code-review r5)."""
    fake_gcp.rr_states = ['SUCCEEDED']
    gcp_instance.run_instances('us-central2', 'us-central2-b', 'drc',
                               _gpu_config(count=2, gpu_dws=True))
    assert len(fake_gcp.vms) == 2
    # Reclamation: VMs vanish, MIG + old SUCCEEDED request persist.
    for name in list(fake_gcp.vms):
        fake_gcp.vms.pop(name)
    fake_gcp.migs['xsky-mig-drc']['instances'].clear()
    fake_gcp.rr_states = ['SUCCEEDED']
    record = gcp_instance.run_instances(
        'us-central2', 'us-central2-b', 'drc',
        _gpu_config(count=2, gpu_dws=True))
    assert len(record.created_instance_ids) == 2
    assert len(fake_gcp.vms) == 2
