"""OCI REST transport: draft-cavage HTTP signatures, no SDK.

Role twin of the reference's oci adaptor + query_helper
(sky/adaptors/oci.py, sky/provision/oci/query_utils.py), redesigned for
this repo's transport pattern (provision/*/rest.py): a `call()` that
signs each request with the tenancy's API key (RSA-SHA256 over the
canonical signing string — `(request-target)`, host, date, and for
bodied requests content-length/content-type/x-content-sha256) and maps
OCI service errors onto the failover engine's typed taxonomy.

Credentials come from the standard ~/.oci/config INI (user / tenancy /
fingerprint / key_file / region) — the same file the reference mounts
onto controllers, so existing OCI setups work unchanged.
"""
from __future__ import annotations

import base64
import configparser
import datetime
import email.utils
import hashlib
import json
import os
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions

CONFIG_PATH = '~/.oci/config'
_MAX_ATTEMPTS = 4
_BACKOFF_S = 2.0

# Service endpoints are regional: https://<service>.<region>.oraclecloud.com
_SERVICE_HOSTS = {
    'iaas': 'iaas.{region}.oraclecloud.com',           # core compute
    'identity': 'identity.{region}.oraclecloud.com',
}
API_VERSION = '20160918'


class OciApiError(Exception):

    def __init__(self, status: int, code: str, message: str) -> None:
        super().__init__(f'{code or status}: {message}')
        self.status = status
        self.code = code or str(status)
        self.message = message


def load_profile(profile: str = 'DEFAULT') -> Optional[Dict[str, str]]:
    path = os.path.expanduser(CONFIG_PATH)
    if not os.path.exists(path):
        return None
    parser = configparser.ConfigParser()
    try:
        parser.read(path)
    except configparser.Error:
        return None
    if profile not in parser and profile != 'DEFAULT':
        return None
    section = parser[profile] if profile in parser else parser['DEFAULT']
    needed = ('user', 'tenancy', 'fingerprint', 'key_file', 'region')
    if not all(k in section for k in needed):
        return None
    return {k: section[k] for k in section}


def classify_error(e: OciApiError,
                   region: Optional[str] = None) -> Exception:
    """Map OCI service error codes onto the failover taxonomy.

    OCI's capacity signal is a 500 InternalError with 'Out of host
    capacity' (their documented stockout response for launch), plus
    LimitExceeded / QuotaExceeded 400s for account limits.
    """
    text = f'{e.code} {e.message}'.lower()
    where = f' in {region}' if region else ''
    if 'out of host capacity' in text or 'outofcapacity' in text:
        return exceptions.CapacityError(f'OCI capacity{where}: {e}')
    if e.code in ('LimitExceeded', 'QuotaExceeded') or 'quota' in text:
        return exceptions.QuotaExceededError(f'OCI quota{where}: {e}')
    if e.status in (401, 403) or e.code == 'NotAuthenticated':
        return exceptions.PermissionError_(f'OCI auth: {e}')
    if e.status == 400 or e.code == 'InvalidParameter':
        return exceptions.InvalidRequestError(f'OCI request: {e}')
    return exceptions.ProvisionError(f'OCI API{where}: {e}')


class Transport:
    """Signed OCI REST calls for one profile + region."""

    def __init__(self, region: Optional[str] = None,
                 profile: str = 'DEFAULT') -> None:
        cfg = load_profile(profile)
        if cfg is None:
            raise exceptions.PermissionError_(
                f'OCI config not found/incomplete at {CONFIG_PATH}.')
        self._cfg = cfg
        self.region = region or cfg['region']
        self.tenancy = cfg['tenancy']
        self._key_id = (f'{cfg["tenancy"]}/{cfg["user"]}/'
                        f'{cfg["fingerprint"]}')
        self._private_key = None  # lazy: loaded on first call

    def _load_key(self):
        if self._private_key is None:
            from cryptography.hazmat.primitives import serialization
            with open(os.path.expanduser(self._cfg['key_file']),
                      'rb') as f:
                self._private_key = serialization.load_pem_private_key(
                    f.read(),
                    password=(self._cfg.get('pass_phrase') or
                              '').encode() or None)
        return self._private_key

    def _sign(self, signing_string: str) -> str:
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import padding
        sig = self._load_key().sign(signing_string.encode(),
                                    padding.PKCS1v15(), hashes.SHA256())
        return base64.b64encode(sig).decode()

    def call(self, method: str, path: str,
             body: Optional[Dict[str, Any]] = None,
             query: Optional[Dict[str, Any]] = None,
             service: str = 'iaas') -> Any:
        host = _SERVICE_HOSTS[service].format(region=self.region)
        target = f'/{API_VERSION}{path}'
        if query:
            target += '?' + urllib.parse.urlencode(
                {k: v for k, v in query.items() if v is not None})
        date = email.utils.format_datetime(
            datetime.datetime.now(datetime.timezone.utc), usegmt=True)
        data = json.dumps(body).encode() if body is not None else None
        if data is None and method.upper() in ('POST', 'PUT', 'PATCH'):
            # OCI requires the body headers on every POST/PUT/PATCH —
            # bodyless actions (e.g. instance START/STOP) sign an empty
            # body or the service rejects the signature.
            data = b''

        headers_order: List[str] = ['(request-target)', 'host', 'date']
        lines = [f'(request-target): {method.lower()} {target}',
                 f'host: {host}', f'date: {date}']
        req_headers = {'host': host, 'date': date,
                       'accept': 'application/json'}
        if data is not None:
            sha = base64.b64encode(hashlib.sha256(data).digest()).decode()
            headers_order += ['content-length', 'content-type',
                              'x-content-sha256']
            lines += [f'content-length: {len(data)}',
                      'content-type: application/json',
                      f'x-content-sha256: {sha}']
            req_headers.update({'content-type': 'application/json',
                                'x-content-sha256': sha})
        signature = self._sign('\n'.join(lines))
        req_headers['authorization'] = (
            'Signature version="1",'
            f'keyId="{self._key_id}",algorithm="rsa-sha256",'
            f'headers="{" ".join(headers_order)}",'
            f'signature="{signature}"')

        url = f'https://{host}{target}'
        for attempt in range(_MAX_ATTEMPTS):
            req = urllib.request.Request(url, data=data, method=method,
                                         headers=req_headers)
            try:
                with urllib.request.urlopen(req, timeout=60) as resp:
                    payload = resp.read()
                    result = json.loads(payload) if payload else {}
                    next_page = resp.headers.get('opc-next-page')
                    # List endpoints paginate via opc-next-page; follow
                    # it so a busy compartment never hides cluster
                    # nodes beyond page one (duplicate-launch /
                    # missed-terminate hazard).
                    if (next_page and method == 'GET'
                            and isinstance(result, list)):
                        rest_pages = self.call(
                            method, path, body=body,
                            query=dict(query or {}, page=next_page),
                            service=service)
                        if isinstance(rest_pages, list):
                            result = result + rest_pages
                    return result
            except urllib.error.HTTPError as e:
                if e.code in (429, 503) and attempt < _MAX_ATTEMPTS - 1:
                    time.sleep(_BACKOFF_S * (attempt + 1))
                    continue
                try:
                    err = json.loads(e.read() or b'{}')
                    raise OciApiError(e.code, err.get('code', ''),
                                      err.get('message', str(e)))
                except (ValueError, AttributeError):
                    raise OciApiError(e.code, '', str(e)) from e
            except urllib.error.URLError as e:
                raise exceptions.ProvisionError(
                    f'OCI API unreachable: {e}') from e
        # Unreachable: every iteration returns or raises (a final-attempt
        # 429/503 raises OciApiError above).
