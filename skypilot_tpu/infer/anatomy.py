"""Per-request anatomy recorder: where a request's lifetime went.

The SLO plane (serve/slo.py) can say *that* p99 TTFT burned; this
module says *where one request's time went* — the replica-side half of
the cross-hop waterfall `xsky serve trace` renders. Each finished
orchestrator Request carries phase accumulators maintained by the
orchestrator (pure float adds on the tick path — decode ticks amortize
ONE timestamp pair per fused batch of steps, attributed to the slots
resident that tick, never per token); this module folds them into one
bounded ring record per request.

Phase taxonomy (replica-side; the LB contributes lb_queue and the
relay remainder, see serve/slo.py's join):

  replica_queue   submit → first admission attempt took the request
  admit_deferred  parked in the deferred list waiting for KV headroom
  prefill         admission → first token in the slot cache
  decode          fused decode dispatch + device wait (batch-amortized)
  sampling_commit host commit of device tokens (batch-amortized)
  finish          unattributed remainder (handler wait, polling gaps)

Sealing happens on HTTP handler threads AFTER the request finished —
never inside ``Orchestrator.step``/``_decode_tick*`` (the xskylint
hot-path-purity closure stays clean; ``AnatomyLog.seal`` is itself a
declared hot-path entry so the lint proves the append blocks on
nothing). ``XSKY_ANATOMY=0`` disables both the tick-path accumulators
and sealing — the bench_decode paired-difference rung's baseline arm.
"""
from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Dict, List, Optional

ENV_ANATOMY = 'XSKY_ANATOMY'
ENV_RING = 'XSKY_ANATOMY_RING_SIZE'

#: Replica-side phases, in waterfall order. The cross-hop join in
#: serve/slo.py prepends lb_queue/relay_connect from the LB record.
PHASES = ('replica_queue', 'admit_deferred', 'prefill', 'decode',
          'sampling_commit', 'finish')


def enabled() -> bool:
    return os.environ.get(ENV_ANATOMY, '1') != '0'


class AnatomyLog:
    """Bounded ring of sealed per-request anatomy records.

    Thread-safe; every mutator is one deque append under a short
    module lock (an infer-module lock, not a control-plane one), so
    record-keeping stays off the relay's and the tick's critical
    paths. Sized by ``XSKY_ANATOMY_RING_SIZE`` (default 2048 — the
    same ring-vs-burn-window sizing note as the LB request ring).
    """

    def __init__(self, maxlen: Optional[int] = None) -> None:
        if maxlen is None:
            try:
                maxlen = int(os.environ.get(ENV_RING, '2048'))
            except ValueError:
                # A typo'd observability knob must not take down the
                # data path it observes (RequestLog posture).
                maxlen = 2048
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=max(1, maxlen))

    def seal(self, request: Any, outcome: str = 'ok'
             ) -> Optional[Dict[str, Any]]:
        """Fold a finished orchestrator Request's accumulated phase
        timers into one anatomy record and append it. Returns the
        record (None when the request never got timestamps — e.g.
        submit itself failed). Called from handler threads only."""
        sub = request.submitted_at
        end = request.finished_at
        if not sub or end is None:
            return None
        total = max(0.0, end - sub)
        taken = request.taken_at
        first = request.first_token_at
        deferred = max(0.0, request.deferred_wait)
        replica_queue = max(0.0, (taken if taken is not None
                                  else end) - sub)
        prefill = 0.0
        if taken is not None and first is not None:
            prefill = max(0.0, first - taken - deferred)
        decode = max(0.0, request.decode_s)
        commit = max(0.0, request.commit_s)
        attributed = (replica_queue + deferred + prefill + decode +
                      commit)
        phases = {
            'replica_queue': replica_queue,
            'admit_deferred': deferred,
            'prefill': prefill,
            'decode': decode,
            'sampling_commit': commit,
            'finish': max(0.0, total - attributed),
        }
        rec = {
            'ts': time.time(),
            'request_id': (request.client_request_id
                           or str(request.request_id)),
            'trace_id': request.trace_id,
            'outcome': outcome,
            'total_s': total,
            'prompt_tokens': len(request.prompt_tokens),
            'output_tokens': len(request.output_tokens),
            'kv_headroom_at_admit': request.kv_headroom_at_admit,
            'phases': phases,
        }
        with self._lock:
            self._ring.append(rec)
        return rec

    def records(self, limit: Optional[int] = None,
                request_id: Optional[str] = None
                ) -> List[Dict[str, Any]]:
        """Newest-first copies, optionally filtered to one request id
        (either the LB-minted id or the orchestrator's numeric one)."""
        with self._lock:
            rows = list(self._ring)
        rows.reverse()
        if request_id is not None:
            rows = [r for r in rows if r['request_id'] == request_id]
        if limit is not None:
            rows = rows[:max(0, int(limit))]
        return [dict(r) for r in rows]


_log: Optional[AnatomyLog] = None
_log_lock = threading.Lock()


def get_log() -> AnatomyLog:
    """Process-wide recorder (lazy: the ring-size env is read at first
    use, so tests that set it before serving see it honored)."""
    global _log
    with _log_lock:
        if _log is None:
            _log = AnatomyLog()
        return _log


def reset_for_test() -> None:
    global _log
    with _log_lock:
        _log = None
