"""Qwen-family decoder-only transformer (third dense family).

Capability twin of the reference's Qwen serving recipes (llm/qwen/);
in-tree like llama.py/gemma.py so the trainer gets it for free.
Architecturally distinct from Llama where Qwen actually differs:

  * Qwen-2: biases on the Q/K/V projections (none elsewhere);
  * Qwen-3: per-head QK-RMSNorm instead of projection biases;
  * long-context RoPE base (theta = 1e6);
  * untied LM head (like Llama, unlike Gemma), so the chunked-CE
    scan from llama.py applies unchanged at long sequence.

Same functional surface as the other families (CONFIGS, logical_axes,
init, forward, loss_fn) and the same logical sharding axes, so the
trainer dispatches on config type alone.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from skypilot_tpu.models import llama
from skypilot_tpu.ops import attention as attention_ops
from skypilot_tpu.ops import quantization as qops
from skypilot_tpu.parallel import mesh as mesh_lib

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class QwenConfig:
    vocab_size: int = 152_064
    d_model: int = 3584
    n_layers: int = 28
    n_heads: int = 28
    n_kv_heads: int = 4
    head_dim: int = 128
    d_ff: int = 18_944
    max_seq_len: int = 32_768
    rope_theta: float = 1e6
    norm_eps: float = 1e-6
    qkv_bias: bool = True      # Qwen-2 style
    qk_norm: bool = False      # Qwen-3 style
    dtype: Any = jnp.bfloat16
    remat: bool = True
    remat_policy: str = 'dots'
    attention_impl: str = 'auto'
    ce_chunk: int = 2048
    # Packed-sequence training (see llama.LlamaConfig.packing_reset_eos).
    packing_reset_eos: Optional[int] = None

    def num_params(self) -> int:
        d, f, v = self.d_model, self.d_ff, self.vocab_size
        hd, h, kv = self.head_dim, self.n_heads, self.n_kv_heads
        attn = d * h * hd * 2 + d * kv * hd * 2
        if self.qkv_bias:
            attn += h * hd + 2 * kv * hd
        if self.qk_norm:
            attn += 2 * hd
        mlp = 3 * d * f
        per_layer = attn + mlp + 2 * d
        return v * d * 2 + self.n_layers * per_layer + d

    def train_flops_per_token(self) -> float:
        attn_flops = (12 * self.n_layers * self.n_heads * self.head_dim *
                      self.max_seq_len)
        return 6 * self.num_params() + attn_flops


QWEN2_7B = QwenConfig()
QWEN3_8B = QwenConfig(vocab_size=151_936, d_model=4096, n_layers=36,
                      n_heads=32, n_kv_heads=8, head_dim=128,
                      d_ff=12_288, qkv_bias=False, qk_norm=True)
QWEN_TINY = QwenConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                       n_kv_heads=2, head_dim=16, d_ff=128,
                       max_seq_len=128, remat=False)
QWEN3_TINY = dataclasses.replace(QWEN_TINY, qkv_bias=False, qk_norm=True)

CONFIGS = {
    'qwen2-7b': QWEN2_7B,
    'qwen3-8b': QWEN3_8B,
    'qwen-tiny': QWEN_TINY,
    'qwen3-tiny': QWEN3_TINY,
}


def logical_axes(config: QwenConfig) -> Params:
    layer = {
        'wq': ('layers', 'embed', 'heads'),
        'wk': ('layers', 'embed', 'kv'),
        'wv': ('layers', 'embed', 'kv'),
        'wo': ('layers', 'heads', 'embed'),
        'w_gate': ('layers', 'embed', 'mlp'),
        'w_up': ('layers', 'embed', 'mlp'),
        'w_down': ('layers', 'mlp', 'embed'),
        'attn_norm': ('layers', 'embed'),
        'mlp_norm': ('layers', 'embed'),
    }
    if config.qkv_bias:
        layer.update({
            'bq': ('layers', 'heads'),
            'bk': ('layers', 'kv'),
            'bv': ('layers', 'kv'),
        })
    if config.qk_norm:
        # Per-head-dim scales, shared across heads (Qwen-3).
        layer.update({
            'q_norm': ('layers', None),
            'k_norm': ('layers', None),
        })
    return {
        'embed': ('vocab', 'embed'),
        'layers': layer,
        'final_norm': ('embed',),
        'lm_head': ('embed', 'vocab'),
    }


def init(config: QwenConfig, key: jax.Array) -> Params:
    c = config
    hd = c.head_dim
    keys = jax.random.split(key, 9)

    def dense(k, shape, fan_in):
        return (jax.random.truncated_normal(k, -2, 2, shape, jnp.float32) *
                (fan_in ** -0.5)).astype(c.dtype)

    def stack(k, shape, fan_in):
        return dense(k, (c.n_layers,) + shape, fan_in)

    layers: Params = {
        'wq': stack(keys[1], (c.d_model, c.n_heads * hd), c.d_model),
        'wk': stack(keys[2], (c.d_model, c.n_kv_heads * hd), c.d_model),
        'wv': stack(keys[3], (c.d_model, c.n_kv_heads * hd), c.d_model),
        'wo': stack(keys[4], (c.n_heads * hd, c.d_model), c.n_heads * hd),
        'w_gate': stack(keys[5], (c.d_model, c.d_ff), c.d_model),
        'w_up': stack(keys[6], (c.d_model, c.d_ff), c.d_model),
        'w_down': stack(keys[7], (c.d_ff, c.d_model), c.d_ff),
        'attn_norm': jnp.ones((c.n_layers, c.d_model), c.dtype),
        'mlp_norm': jnp.ones((c.n_layers, c.d_model), c.dtype),
    }
    if c.qkv_bias:
        layers.update({
            'bq': jnp.zeros((c.n_layers, c.n_heads * hd), c.dtype),
            'bk': jnp.zeros((c.n_layers, c.n_kv_heads * hd), c.dtype),
            'bv': jnp.zeros((c.n_layers, c.n_kv_heads * hd), c.dtype),
        })
    if c.qk_norm:
        layers.update({
            'q_norm': jnp.ones((c.n_layers, hd), c.dtype),
            'k_norm': jnp.ones((c.n_layers, hd), c.dtype),
        })
    return {
        'embed': dense(keys[0], (c.vocab_size, c.d_model), c.d_model),
        'layers': layers,
        'final_norm': jnp.ones((c.d_model,), c.dtype),
        'lm_head': dense(keys[8], (c.d_model, c.vocab_size), c.d_model),
    }


def _layer(config: QwenConfig, mesh: Optional[mesh_lib.Mesh],
           x: jax.Array, lp: Params, positions: jax.Array,
           kv_cache=None, cache_positions: Optional[jax.Array] = None,
           return_kv: bool = False,
           segment_ids: Optional[jax.Array] = None):
    """One block. Training/prefill by default; with kv_cache set, a
    decode step writing each slot's new K/V at its own position (same
    contract as llama._layer's continuous-batching path)."""
    c = config
    hd = c.head_dim
    b, s, _ = x.shape

    def shard(arr, axes):
        if mesh is None:
            return arr
        return mesh_lib.shard_logical(arr, mesh, axes)

    h = llama._rms_norm(x, lp['attn_norm'], c.norm_eps)
    q = llama._ckpt_name(qops.matmul(h, lp['wq']), 'attn_q')
    k = llama._ckpt_name(qops.matmul(h, lp['wk']), 'attn_k')
    v = llama._ckpt_name(qops.matmul(h, lp['wv']), 'attn_v')
    if c.qkv_bias:
        q, k, v = q + lp['bq'], k + lp['bk'], v + lp['bv']
    q = q.reshape(b, s, c.n_heads, hd)
    k = k.reshape(b, s, c.n_kv_heads, hd)
    v = v.reshape(b, s, c.n_kv_heads, hd)
    if c.qk_norm:
        q = llama._rms_norm(q, lp['q_norm'], c.norm_eps)
        k = llama._rms_norm(k, lp['k_norm'], c.norm_eps)
    q = shard(q, ('batch', 'activation_length', 'activation_heads', None))
    k = shard(k, ('batch', 'activation_length', 'activation_kv', None))
    q = llama._rope(q, positions, c.rope_theta)
    k = llama._rope(k, positions, c.rope_theta)

    if kv_cache is not None:
        attn, new_cache = llama.slot_cache_attend(
            q, k, v, kv_cache, cache_positions=cache_positions,
            mesh=mesh)
    else:
        new_cache = (k, v) if return_kv else None
        attn = attention_ops.dot_product_attention(
            q, k, v, causal=True, implementation=c.attention_impl,
            segment_ids=segment_ids)
    attn = attn.reshape(b, s, c.n_heads * hd)
    x = x + shard(llama._ckpt_name(qops.matmul(attn, lp['wo']), 'attn_o'),
                  ('batch', 'activation_length', 'activation_embed'))

    h = llama._rms_norm(x, lp['mlp_norm'], c.norm_eps)
    gate = jax.nn.silu(
        llama._ckpt_name(qops.matmul(h, lp['w_gate']), 'mlp_gate').astype(jnp.float32))
    up = llama._ckpt_name(qops.matmul(h, lp['w_up']), 'mlp_up').astype(jnp.float32)
    ff = shard((gate * up).astype(c.dtype),
               ('batch', 'activation_length', 'activation_mlp'))
    x = x + shard(qops.matmul(ff, lp['w_down']),
                  ('batch', 'activation_length', 'activation_embed'))
    return x, new_cache


def _trunk(config: QwenConfig, params: Params, tokens: jax.Array,
           positions: Optional[jax.Array],
           mesh: Optional[mesh_lib.Mesh],
           return_kv: bool = False):
    c = config
    segment_ids = None
    if positions is None:
        segment_ids, positions = llama.positions_and_segments(
            c, tokens, serving=return_kv)
    x = llama._embed_lookup(params['embed'], tokens, mesh).astype(c.dtype)
    if mesh is not None:
        x = mesh_lib.shard_logical(
            x, mesh, ('batch', 'activation_length', 'activation_embed'))

    def layer_fn(x, lp):
        x, kv = _layer(c, mesh, x, lp, positions, return_kv=return_kv,
                       segment_ids=segment_ids)
        return x, ({'k': kv[0], 'v': kv[1]} if return_kv else None)

    if c.remat and not return_kv:
        layer_fn = jax.checkpoint(layer_fn, policy=llama._remat_policy(c))
    x, kv = jax.lax.scan(layer_fn, x, params['layers'])
    return llama._rms_norm(x, params['final_norm'], c.norm_eps), kv


def prefill_hidden(config: QwenConfig, params: Params, tokens: jax.Array,
                   true_len: jax.Array,
                   mesh: Optional[mesh_lib.Mesh] = None):
    """Prefill trunk → (last_hidden [B, D], per-layer KV) — the same
    engine contract as llama.prefill_hidden."""
    x, kv = _trunk(config, params, tokens, None, mesh, return_kv=True)
    return llama.last_token_hidden(x, true_len), kv


def decode_forward(config: QwenConfig, params: Params,
                   last_tokens: jax.Array, positions: jax.Array,
                   kv, mesh: Optional[mesh_lib.Mesh] = None):
    """One decode step for a batch of slots (llama.decode_forward twin)."""
    c = config
    x = qops.embed_rows(params['embed'], last_tokens[:, None]).astype(c.dtype)
    pos = positions[:, None]

    def layer_fn(x, scanned):
        lp, ck, cv = scanned
        x, new_cache = _layer(c, mesh, x, lp, pos, kv_cache=(ck, cv),
                              cache_positions=positions)
        return x, {'k': new_cache[0], 'v': new_cache[1]}

    x, new_kv = jax.lax.scan(layer_fn, x, (params['layers'],
                                           kv['k'], kv['v']))
    x = llama._rms_norm(x, params['final_norm'], c.norm_eps)
    return lm_logits(c, params, x)[:, 0], new_kv


def verify_forward(config: QwenConfig, params: Params,
                   tokens: jax.Array, positions: jax.Array, kv,
                   mesh: Optional[mesh_lib.Mesh] = None):
    """Multi-token decode for speculative verification
    (llama.verify_forward twin): tokens/positions [B, S] →
    (logits [B, S, V], new kv)."""
    c = config
    x = qops.embed_rows(params['embed'], tokens).astype(c.dtype)

    def layer_fn(x, scanned):
        lp, ck, cv = scanned
        x, new_cache = _layer(c, mesh, x, lp, positions,
                              kv_cache=(ck, cv),
                              cache_positions=positions)
        return x, {'k': new_cache[0], 'v': new_cache[1]}

    x, new_kv = jax.lax.scan(layer_fn, x, (params['layers'],
                                           kv['k'], kv['v']))
    x = llama._rms_norm(x, params['final_norm'], c.norm_eps)
    return lm_logits(c, params, x), new_kv


def forward(config: QwenConfig, params: Params, tokens: jax.Array,
            mesh: Optional[mesh_lib.Mesh] = None,
            positions: Optional[jax.Array] = None) -> jax.Array:
    """Training forward → fp32 logits [B, S, vocab]."""
    x, _ = _trunk(config, params, tokens, positions, mesh)
    return qops.matmul(x, params['lm_head'],
                       preferred_element_type=jnp.float32)


def loss_fn(config: QwenConfig, params: Params, tokens: jax.Array,
            targets: jax.Array, mesh: Optional[mesh_lib.Mesh] = None,
            loss_mask: Optional[jax.Array] = None) -> jax.Array:
    """Mean next-token CE; reuses llama's chunked large-vocab scan."""
    x, _ = _trunk(config, params, tokens, None, mesh)
    return llama._chunked_ce(x, params['lm_head'], targets, loss_mask,
                             config.ce_chunk)


def pipelined_loss_fn(config: QwenConfig, params: Params,
                      tokens: jax.Array, targets: jax.Array,
                      mesh: mesh_lib.Mesh, n_microbatches: int,
                      loss_mask: Optional[jax.Array] = None) -> jax.Array:
    """loss_fn with the layer stack pipelined over the 'stage' axis
    (same GPipe schedule as llama.pipelined_loss_fn; the pipeline region
    is family-agnostic, only the layer body differs)."""
    from skypilot_tpu.parallel import pipeline as pipeline_lib
    c = config
    x = llama._embed_lookup(params['embed'], tokens, mesh).astype(c.dtype)

    def one_layer(x_mb: jax.Array, lp: Params) -> jax.Array:
        b, s, _ = x_mb.shape
        pos = jnp.broadcast_to(jnp.arange(s)[None, :], (b, s))
        y, _ = _layer(c, None, x_mb, lp, pos)
        return y

    x = pipeline_lib.pipeline_apply(one_layer, params['layers'], x, mesh,
                                    n_microbatches, remat=c.remat)
    x = llama._rms_norm(x, params['final_norm'], c.norm_eps)
    return llama._chunked_ce(x, params['lm_head'], targets, loss_mask,
                             config.ce_chunk)


def lm_logits(config, params: Params, hidden: jax.Array) -> jax.Array:
    """Untied LM head (same structure as llama's)."""
    return llama.lm_logits(None, params, hidden)
