"""SCP provisioner op-set (virtual servers via the nodepool base).

Behavioral twin of sky/provision/scp/instance.py, reshaped to the
shared nodepool lifecycle: membership rides the virtual-server NAME
(`<cluster>-<index>`), stored server-side. Platform facts: zonal
service zones (the catalog region is the service zone), stop/start
supported, one NAT/public IP per server when assigned, no spot market;
servers need a service zone + subnet + image, auto-discovered from the
project (first available of each), with the SSH key injected via the
init script — the same bring-up the reference drives through its VPC
helpers (sky/provision/scp/config.py).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import common
from skypilot_tpu.provision import nodepool
from skypilot_tpu.provision.scp import rest

_transport_factory = rest.Transport


def set_transport_factory(factory) -> None:
    global _transport_factory
    _transport_factory = factory


class ScpApi(nodepool.NodeApi):
    provider_name = 'scp'
    ssh_user = 'root'
    supports_stop = True
    state_map = {
        'creating': 'PENDING',
        'editing': 'PENDING',
        'starting': 'PENDING',
        'restarting': 'PENDING',
        'running': 'RUNNING',
        'stopping': 'STOPPING',
        'stopped': 'STOPPED',
        'terminating': None,
        'terminated': None,
        'error': None,
    }

    def __init__(self) -> None:
        self.t = _transport_factory()

    @staticmethod
    def _row(vs: Dict[str, Any]) -> Dict[str, Any]:
        return {'id': vs.get('virtualServerId'),
                'name': vs.get('virtualServerName', ''),
                'status': vs.get('virtualServerState', ''),
                'public_ip': vs.get('natIpAddress') or
                vs.get('publicIpAddress'),
                'private_ip': vs.get('ip') or vs.get('ipAddress')}

    def list_nodes(self) -> List[Dict[str, Any]]:
        reply = self.t.call('GET',
                            '/virtual-server/v2/virtual-servers')
        return [self._row(vs) for vs in reply.get('contents', [])]

    def _service_zone(self, region: str) -> str:
        zones = self.t.call(
            'GET', '/project/v3/projects/zones').get('contents', [])
        for z in zones:
            if z.get('serviceZoneName') == region or \
                    z.get('serviceZoneLocation') == region:
                return z['serviceZoneId']
        if zones:
            return zones[0]['serviceZoneId']
        raise exceptions.ProvisionError('SCP project has no '
                                        'service zones.')

    def _subnet(self, zone_id: str) -> str:
        subnets = self.t.call('GET', '/subnet/v2/subnets').get(
            'contents', [])
        for s in subnets:
            if s.get('serviceZoneId') in (None, zone_id) and \
                    s.get('subnetState') in (None, 'ACTIVE'):
                return s['subnetId']
        raise exceptions.ProvisionError(
            'No SCP subnet found; create a VPC + subnet first.')

    def _image(self, zone_id: str, image_id: Optional[str]) -> str:
        if image_id:
            return image_id
        images = self.t.call(
            'GET', '/image/v2/standard-images',
            query={'serviceZoneId': zone_id}).get('contents', [])
        for img in images:
            if 'ubuntu' in (img.get('imageName') or '').lower():
                return img['imageId']
        if images:
            return images[0]['imageId']
        raise exceptions.ProvisionError('No SCP standard image found.')

    def create_node(self, name: str, region: str, zone: Optional[str],
                    node_config: Dict[str, Any]) -> str:
        del zone
        import os
        from skypilot_tpu import authentication
        _, public_key_path = authentication.get_or_generate_keys()
        with open(os.path.expanduser(public_key_path),
                  encoding='utf-8') as f:
            public_key = f.read().strip()
        zone_id = self._service_zone(region)
        init_script = ('#!/bin/bash\n'
                       'mkdir -p /root/.ssh\n'
                       f"echo '{public_key}' >> "
                       '/root/.ssh/authorized_keys\n')
        reply = self.t.call('POST',
                            '/virtual-server/v4/virtual-servers', {
                                'virtualServerName': name,
                                'serviceZoneId': zone_id,
                                'serverType':
                                    node_config['instance_type'],
                                'imageId': self._image(
                                    zone_id, node_config.get('image_id')),
                                'subnetId': self._subnet(zone_id),
                                'blockStorage': {
                                    'diskSize':
                                        node_config.get('disk_size', 100),
                                },
                                'nicList': [{'natEnabled': True}],
                                'initialScriptContent': init_script,
                                'osAdmin': {'osUserId': 'root'},
                            })
        return str(reply.get('resourceId') or
                   reply.get('virtualServerId') or name)

    def delete_node(self, node_id: str) -> None:
        self.t.call('DELETE',
                    f'/virtual-server/v2/virtual-servers/{node_id}')

    def stop_node(self, node_id: str) -> None:
        self.t.call('POST',
                    f'/virtual-server/v2/virtual-servers/{node_id}/stop')

    def start_node(self, node_id: str) -> None:
        self.t.call(
            'POST',
            f'/virtual-server/v2/virtual-servers/{node_id}/start')

    def classify(self, e: Exception,
                 region: Optional[str] = None) -> Exception:
        if isinstance(e, rest.ScpApiError):
            return rest.classify_error(e, region)
        return e


def _api(provider_config: Dict[str, Any]) -> ScpApi:
    del provider_config
    return ScpApi()


def run_instances(region: str, zone: Optional[str], cluster_name: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    return nodepool.run_instances(_api(config.provider_config), region,
                                  zone, cluster_name, config)


def wait_instances(region: str, cluster_name: str, state: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   timeout_s: float = 900.0,
                   poll_interval_s: float = 5.0) -> None:
    del region
    nodepool.wait_instances(_api(provider_config or {}), cluster_name,
                            state, timeout_s, poll_interval_s)


def stop_instances(cluster_name: str,
                   provider_config: Dict[str, Any]) -> None:
    nodepool.stop_instances(_api(provider_config), cluster_name)


def terminate_instances(cluster_name: str,
                        provider_config: Dict[str, Any]) -> None:
    nodepool.terminate_instances(_api(provider_config), cluster_name)


def query_instances(cluster_name: str, provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    return nodepool.query_instances(_api(provider_config), cluster_name)


def get_cluster_info(region: str, cluster_name: str,
                     provider_config: Dict[str, Any]
                     ) -> common.ClusterInfo:
    del region
    return nodepool.get_cluster_info(_api(provider_config), cluster_name,
                                     provider_config)


def open_ports(cluster_name: str, ports: List[str],
               provider_config: Dict[str, Any]) -> None:
    # Port policy rides the project's security groups / firewalls,
    # which SCP scopes per VPC; NAT-enabled NICs default-allow the
    # provisioned service ports. Managed per project, not per cluster.
    del cluster_name, ports, provider_config


def cleanup_ports(cluster_name: str,
                  provider_config: Dict[str, Any]) -> None:
    del cluster_name, provider_config
