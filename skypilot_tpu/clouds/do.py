"""DigitalOcean: GPU droplets for cross-cloud optimization.

Lean twin of sky/clouds/do.py — catalog-backed feasibility via
CatalogCloud, deploy variables for the 'do' provisioner. Platform
facts: flat regions, stop/start via power actions, all ports open,
no spot market, GPU droplets (H100/L40S/MI300X) in nyc2/tor1/atl1.
"""
from __future__ import annotations

import os
import typing
from typing import Any, Dict, Optional, Tuple

from skypilot_tpu.clouds import catalog_cloud
from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.utils import registry

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib


@registry.CLOUD_REGISTRY.register(aliases=['digitalocean'])
class DO(catalog_cloud.CatalogCloud):
    _REPR = 'DO'

    _UNSUPPORTED = {
        cloud_lib.CloudImplementationFeatures.SPOT_INSTANCE:
            'DigitalOcean has no spot market.',
        cloud_lib.CloudImplementationFeatures.CUSTOM_DISK_TIER:
            'DigitalOcean droplets have fixed disks per size.',
    }

    @property
    def provisioner_module(self) -> str:
        return 'do'

    def unsupported_features_for_resources(
            self, resources: 'resources_lib.Resources'
    ) -> Dict[cloud_lib.CloudImplementationFeatures, str]:
        return dict(self._UNSUPPORTED)

    def make_deploy_resources_variables(
            self, resources: 'resources_lib.Resources', cluster_name: str,
            region: str, zone: Optional[str]) -> Dict[str, Any]:
        vars: Dict[str, Any] = {
            'cluster_name': cluster_name,
            'region': region,
            'zone': None,
            'instance_type': resources.instance_type,
            'image_id': resources.image_id,
            'disk_size': resources.disk_size,
            'use_spot': False,
        }
        if resources.accelerators:
            name, count = next(iter(resources.accelerators.items()))
            vars.update({'gpu_type': name, 'gpu_count': count})
        return vars

    def provider_config_overrides(
            self, node_config: Dict[str, Any]) -> Dict[str, Any]:
        del node_config
        return {}

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        from skypilot_tpu.provision.do import rest
        if rest.load_token() is not None:
            return True, None
        return False, (
            'DigitalOcean token not found. Set $DIGITALOCEAN_TOKEN or '
            f'run `doctl auth init` (writes {rest.CREDENTIALS_PATH}).')

    def get_credential_file_mounts(self) -> Dict[str, str]:
        from skypilot_tpu.provision.do import rest
        if os.path.exists(os.path.expanduser(rest.CREDENTIALS_PATH)):
            return {rest.CREDENTIALS_PATH: rest.CREDENTIALS_PATH}
        return {}

    def get_egress_cost(self, num_gigabytes: float) -> float:
        return num_gigabytes * 0.01
