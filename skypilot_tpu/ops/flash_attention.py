"""Flash attention for TPU in Pallas: forward + FA2-style backward.

Forward: online-softmax over KV blocks, accumulator in VMEM, causal
blocks skipped on the MXU (FlashAttention-2 schedule adapted to the TPU
grid model: the KV dimension is the innermost grid axis and running
stats live in VMEM scratch that persists across grid steps).

Backward: two Pallas kernels recomputing P from the saved LSE —
  * dKV: grid (BH, KV-blocks, Q-blocks), dk/dv accumulate in VMEM
    scratch across the inner Q sweep;
  * dQ: grid (BH, Q-blocks, KV-blocks), dq accumulates across the inner
    KV sweep.
Both skip fully-masked causal blocks (the earlier XLA blockwise backward
computed the full S×S rectangle and materialized P in fp32 — at seq 8K
that doubled the attention FLOPs and blew HBM; the kernels keep P in
VMEM and run the matmuls in bf16 with fp32 accumulation).

Layout convention: q [B, S, H, D], k/v [B, S, Hkv, D]. GQA is native:
K/V stay at their Hkv width in HBM and every kernel resolves the shared
KV head inside its BlockSpec index_map (`bh // groups`), so a 4-group
Llama-3 config streams K/V once instead of four times; the dKV kernel
sweeps the group's query heads in an extra grid dimension so dk/dv
accumulate in VMEM without a reduction pass over replicated heads.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

def _env_block(name: str, default: int) -> int:
    """Block-size override for autotuning (python bench.py autotune):
    sweeping (block_q, block_kv) per chip generation beats guessing —
    the best point moved between v4 and v5e in our measurements."""
    import os
    value = os.environ.get(name)
    return int(value) if value else default


DEFAULT_BLOCK_Q = _env_block('XSKY_FLASH_BLOCK_Q', 512)
DEFAULT_BLOCK_KV = _env_block('XSKY_FLASH_BLOCK_KV', 512)
_NEG_INF = -1e30
_LANES = 128  # row-stat scratch minor dim (TPU lane width)


def _window_kv_first(qi, block_q: int, block_kv: int, window: int):
    """First live KV block index for query block qi under a causal
    sliding window (used by kernels AND BlockSpec index_maps, which
    must agree exactly)."""
    return jnp.maximum(0, (qi * block_q - (window - 1)) // block_kv)


def _window_inner_blocks(num_kv: int, block_q: int, block_kv: int,
                         window: int) -> int:
    """Static inner-grid length: how many KV blocks a query block can
    touch under a causal window (span = window + block_q - 1)."""
    return min(num_kv, (window + block_q - 2) // block_kv + 2)


def _fwd_kernel(q_ref, k_ref, v_ref, seg_q_ref, seg_kv_ref, o_ref,
                lse_ref, acc_ref, m_ref, l_ref, *, scale: float,
                causal: bool, block_q: int, block_kv: int, window,
                num_kv_total: int, segmented: bool, softcap=None):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    num_kv = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = qi * block_q
    # Under a causal window the inner grid walks only the live KV
    # blocks (see _window_kv_first): recover the true block index the
    # BlockSpec index_map fetched.
    if window is not None and causal:
        kv_idx = _window_kv_first(qi, block_q, block_kv, window) + ki
    else:
        kv_idx = ki
    kv_start = kv_idx * block_kv

    # Whole block above the diagonal (or entirely left of the sliding
    # window) → nothing to do: with the remapped grid, out-of-window
    # blocks are neither computed NOR fetched, so work and HBM traffic
    # both scale O(S·W).
    run = kv_idx < num_kv_total
    if causal:
        run = run & (q_start + block_q - 1 >= kv_start)
    if window is not None:
        run = run & (kv_start + block_kv - 1 >= q_start - (window - 1))

    @pl.when(run)
    def _body():
        q = q_ref[0]                                   # [bq, d]
        k = k_ref[0]                                   # [bkv, d]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bkv]
        if softcap is not None:
            # Gemma-2: cap·tanh(s/cap) before masking (matches the
            # XLA reference and HF eager).
            s = softcap * jnp.tanh(s / softcap)
        if causal or window is not None or segmented:
            # Mask only needed on diagonal/window-crossing blocks.
            q_pos = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0)
            kv_pos = kv_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1)
            keep = q_pos >= kv_pos if causal else (q_pos == q_pos)
            if window is not None:
                keep = keep & (q_pos - kv_pos < window)
            if segmented:
                # [bq, 1] == [1, bkv] → block-diagonal document mask.
                keep = keep & (seg_q_ref[0] == seg_kv_ref[0])
            s = jnp.where(keep, s, _NEG_INF)

        m_prev = m_ref[:, 0:1]                         # [bq, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)      # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)                # [bq, 1]
        p = jnp.exp(s - m_new)                         # [bq, bkv]
        l_new = l_ref[:, 0:1] * alpha + jnp.sum(p, axis=1, keepdims=True)
        v = v_ref[0]                                   # [bkv, d]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [bq, d]
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == num_kv - 1)
    def _finalize():
        l = l_ref[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = (m_ref[:] + jnp.log(l_safe)).astype(jnp.float32)


def _seg_views(segment_ids, b):
    """[B, S] int32 segment ids → the two tile-legal kernel views:
    seg_q [B, S, 1] (block (1, bq, 1): last dims (bq, 1) — legal for
    any bq multiple of 8) and seg_kv [B, 1, S] (block (1, 1, bkv)).
    A per-(B, S) 2D operand with a (1, block) block would trip the
    Mosaic last-two-dims tiling rule whenever B > 1."""
    if segment_ids is None:
        dummy = jnp.zeros((1, 1, 1), jnp.int32)
        return dummy, dummy
    seg = segment_ids.astype(jnp.int32)
    assert seg.shape[0] == b, (seg.shape, b)
    return seg[:, :, None], seg[:, None, :]


def _flash_fwd(q: jax.Array, k: jax.Array, v: jax.Array, segment_ids,
               *, causal: bool, block_q: int, block_kv: int,
               window=None, softcap=None, scale_override=None
               ) -> Tuple[jax.Array, jax.Array]:
    """Returns (out [B,H,S,D], lse [B*H,S,LANES] lane-broadcast fp32).

    q is [B,H,S,D]; k/v are [B,Hkv,S,D] — the shared KV head for query
    head bh is fetched via `bh // groups` in the KV index_map, so GQA
    streams each K/V block from HBM once per group, not once per head.

    The LSE stays in the kernels' natural lane-broadcast layout: the
    backward kernels consume it directly, so no reshape/transpose or
    re-broadcast ever touches HBM."""
    b, h, s, d = q.shape
    h_kv = k.shape[1]
    groups = h // h_kv
    s_kv = k.shape[2]
    block_q = min(block_q, s)
    block_kv = min(block_kv, s_kv)
    assert s % block_q == 0 and s_kv % block_kv == 0, (s, s_kv, block_q,
                                                      block_kv)
    num_kv_total = s_kv // block_kv
    if window is not None and causal:
        inner = _window_inner_blocks(num_kv_total, block_q, block_kv,
                                     window)

        def kv_block(qi, ki):
            first = _window_kv_first(qi, block_q, block_kv, window)
            return jnp.minimum(first + ki, num_kv_total - 1)

        def kv_map(bh, qi, ki):
            return (bh // groups, kv_block(qi, ki), 0)
    else:
        inner = num_kv_total

        def kv_block(qi, ki):
            return ki

        def kv_map(bh, qi, ki):
            return (bh // groups, ki, 0)
    grid = (b * h, s // block_q, inner)
    scale = d ** -0.5 if scale_override is None else scale_override

    qr = q.reshape(b * h, s, d)
    kr = k.reshape(b * h_kv, s_kv, d)
    vr = v.reshape(b * h_kv, s_kv, d)
    segmented = segment_ids is not None
    seg_q, seg_kv = _seg_views(segment_ids, b)
    if segmented:
        seg_q_spec = pl.BlockSpec(
            (1, block_q, 1), lambda bh, qi, ki: (bh // h, qi, 0))
        seg_kv_spec = pl.BlockSpec(
            (1, 1, block_kv),
            lambda bh, qi, ki: (bh // h, 0, kv_block(qi, ki)))
    else:
        seg_q_spec = seg_kv_spec = pl.BlockSpec(
            (1, 1, 1), lambda bh, qi, ki: (0, 0, 0))

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_kv=block_kv,
                               window=window, num_kv_total=num_kv_total,
                               segmented=segmented, softcap=softcap)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_kv, d), kv_map),
            pl.BlockSpec((1, block_kv, d), kv_map),
            seg_q_spec,
            seg_kv_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, _LANES),
                         lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, s, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=_should_interpret(),
    )(qr, kr, vr, seg_q, seg_kv)
    return out.reshape(b, h, s, d), lse


def _should_interpret() -> bool:
    return jax.default_backend() != 'tpu'


def _block_p_ds(q, k, v, out, dout, lse_col, *, scale: float,
                causal: bool, q_start, kv_start, block_q: int,
                block_kv: int, window, seg_q=None, seg_kv=None,
                softcap=None):
    """Shared P/dS recompute for both backward kernels.

    q/out/dout [bq, d]; k/v [bkv, d]; lse_col [bq, 1] fp32; seg_q
    [bq, 1] / seg_kv [1, bkv] int32 when packing masks apply. The delta
    row-stat (Σ dO⊙O) is recomputed here from the blocks already in
    VMEM — cheaper than streaming a third stats operand from HBM.
    With `softcap`, P is recomputed through cap·tanh(s/cap) and dS
    carries the (1 - tanh²) chain factor.
    Returns (p, ds) as bf16-castable fp32 [bq, bkv].
    """
    delta_col = jnp.sum(
        dout.astype(jnp.float32) * out.astype(jnp.float32),
        axis=-1, keepdims=True)                            # [bq, 1]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale        # [bq, bkv]
    dcap = None
    if softcap is not None:
        t = jnp.tanh(s / softcap)
        s = softcap * t
        dcap = 1.0 - t * t
    if causal or window is not None or seg_q is not None:
        q_pos = q_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 0)
        kv_pos = kv_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 1)
        keep = q_pos >= kv_pos if causal else (q_pos == q_pos)
        if window is not None:
            keep = keep & (q_pos - kv_pos < window)
        if seg_q is not None:
            keep = keep & (seg_q == seg_kv)
        s = jnp.where(keep, s, _NEG_INF)
    p = jnp.exp(s - lse_col)                               # [bq, bkv]
    dp = jax.lax.dot_general(
        dout, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                # [bq, bkv]
    ds = p * (dp - delta_col) * scale
    if dcap is not None:
        ds = ds * dcap
    return p, ds


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, out_ref, dout_ref, lse_ref,
                    seg_q_ref, seg_kv_ref, dk_ref, dv_ref, dk_acc,
                    dv_acc, *, scale: float, causal: bool, block_q: int,
                    block_kv: int, window, num_q_total: int,
                    segmented: bool, softcap=None):
    """Grid (B*Hkv, KV-blocks, groups, Q-blocks): the two inner sweeps
    walk every query head sharing this KV head and that head's live Q
    blocks, so the GQA gradient reduction (dk/dv summed over the group)
    happens in the VMEM accumulators — no replicated-head HBM pass."""
    kvi = pl.program_id(1)
    gi = pl.program_id(2)
    qi = pl.program_id(3)
    num_g = pl.num_programs(2)
    num_q = pl.num_programs(3)

    @pl.when((gi == 0) & (qi == 0))
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    kv_start = kvi * block_kv
    if window is not None and causal:
        # First live Q block for this KV block: the one containing
        # kv_start (causal lower bound).
        q_idx = kv_start // block_q + qi
    else:
        q_idx = qi
    q_start = q_idx * block_q
    run = q_idx < num_q_total
    if causal:
        run = run & (q_start + block_q - 1 >= kv_start)
    if window is not None:
        run = run & (kv_start + block_kv - 1 >= q_start - (window - 1))

    @pl.when(run)
    def _body():
        q = q_ref[0]
        dout = dout_ref[0]
        p, ds = _block_p_ds(
            q, k_ref[0], v_ref[0], out_ref[0], dout,
            lse_ref[0][:, 0:1], scale=scale,
            causal=causal, q_start=q_start, kv_start=kv_start,
            block_q=block_q, block_kv=block_kv, window=window,
            seg_q=seg_q_ref[0] if segmented else None,
            seg_kv=seg_kv_ref[0] if segmented else None,
            softcap=softcap)
        # dv += Pᵀ dO ; dk += dSᵀ Q  (contract the q dim, bf16 on MXU)
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p.astype(dout.dtype), dout, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when((gi == num_g - 1) & (qi == num_q - 1))
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, out_ref, dout_ref, lse_ref,
                   seg_q_ref, seg_kv_ref, dq_ref, dq_acc, *,
                   scale: float, causal: bool, block_q: int,
                   block_kv: int, window, num_kv_total: int,
                   segmented: bool, softcap=None):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    num_kv = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_start = qi * block_q
    if window is not None and causal:
        kv_idx = _window_kv_first(qi, block_q, block_kv, window) + ki
    else:
        kv_idx = ki
    kv_start = kv_idx * block_kv
    run = kv_idx < num_kv_total
    if causal:
        run = run & (q_start + block_q - 1 >= kv_start)
    if window is not None:
        run = run & (kv_start + block_kv - 1 >= q_start - (window - 1))

    @pl.when(run)
    def _body():
        k = k_ref[0]
        _, ds = _block_p_ds(
            q_ref[0], k, v_ref[0], out_ref[0], dout_ref[0],
            lse_ref[0][:, 0:1], scale=scale,
            causal=causal, q_start=q_start, kv_start=kv_start,
            block_q=block_q, block_kv=block_kv, window=window,
            seg_q=seg_q_ref[0] if segmented else None,
            seg_kv=seg_kv_ref[0] if segmented else None,
            softcap=softcap)
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == num_kv - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_flash(residuals, dout, *, causal: bool, block_q: int,
               block_kv: int, window, softcap=None,
               scale_override=None):
    """FA2 backward: dKV kernel + dQ kernel from the saved LSE.

    q/out/dout are [B,H,S,D]; k/v are [B,Hkv,Skv,D]. dQ resolves the
    shared KV head via `bh // groups` like the forward; dKV runs one
    program per KV head and sweeps (group, Q-block) inner grid dims so
    dk/dv come out at their native Hkv width."""
    q, k, v, segment_ids, out, lse = residuals  # lse [B*H,S,LANES]
    b, h, s, d = q.shape
    h_kv = k.shape[1]
    groups = h // h_kv
    s_kv = k.shape[2]
    scale = d ** -0.5 if scale_override is None else scale_override
    block_q = min(block_q, s)
    block_kv = min(block_kv, s_kv)

    qr = q.reshape(b * h, s, d)
    kr = k.reshape(b * h_kv, s_kv, d)
    vr = v.reshape(b * h_kv, s_kv, d)
    outr = out.reshape(b * h, s, d)
    dor = dout.reshape(b * h, s, d)

    num_q_total = s // block_q
    num_kv_total = s_kv // block_kv
    windowed = window is not None and causal
    if windowed:
        # Inner sweeps walk only the live blocks (DMA included): work
        # and traffic scale O(S·W) like the forward.
        dq_inner = _window_inner_blocks(num_kv_total, block_q, block_kv,
                                        window)
        dkv_inner = min(num_q_total,
                        (block_kv + window - 2) // block_q + 2)

        def dq_kv_block(i, j):
            first = _window_kv_first(i, block_q, block_kv, window)
            return jnp.minimum(first + j, num_kv_total - 1)

        def dkv_q_block(j, i):
            first = (j * block_kv) // block_q
            return jnp.minimum(first + i, num_q_total - 1)
    else:
        dq_inner = num_kv_total
        dkv_inner = num_q_total

        def dq_kv_block(i, j):
            return j

        def dkv_q_block(j, i):
            return i

    def dq_kv_map(bh, i, j):
        return (bh // groups, dq_kv_block(i, j), 0)

    def dkv_q_map(bh, j, g, i):
        return (bh * groups + g, dkv_q_block(j, i), 0)

    q_spec = pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0))
    kv_spec = pl.BlockSpec((1, block_kv, d), dq_kv_map)
    stat_spec = pl.BlockSpec((1, block_q, _LANES),
                             lambda bh, i, j: (bh, i, 0))
    # dKV: outer grid dims are (KV head, KV block); the inner sweeps
    # walk (query head in group, Q block).
    dkv_q_spec = pl.BlockSpec((1, block_q, d), dkv_q_map)
    dkv_kv_spec = pl.BlockSpec((1, block_kv, d),
                               lambda bh, j, g, i: (bh, j, 0))
    dkv_stat_spec = pl.BlockSpec((1, block_q, _LANES), dkv_q_map)

    segmented = segment_ids is not None
    seg_q, seg_kv = _seg_views(segment_ids, b)
    if segmented:
        dkv_seg_q_spec = pl.BlockSpec(
            (1, block_q, 1),
            lambda bh, j, g, i: (bh // h_kv, dkv_q_block(j, i), 0))
        dkv_seg_kv_spec = pl.BlockSpec(
            (1, 1, block_kv), lambda bh, j, g, i: (bh // h_kv, 0, j))
        dq_seg_q_spec = pl.BlockSpec(
            (1, block_q, 1), lambda bh, i, j: (bh // h, i, 0))
        dq_seg_kv_spec = pl.BlockSpec(
            (1, 1, block_kv),
            lambda bh, i, j: (bh // h, 0, dq_kv_block(i, j)))
    else:
        dummy3 = pl.BlockSpec((1, 1, 1), lambda *_: (0, 0, 0))
        dkv_seg_q_spec = dkv_seg_kv_spec = dummy3
        dq_seg_q_spec = dq_seg_kv_spec = dummy3

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_kv=block_kv,
                          window=window, num_q_total=num_q_total,
                          segmented=segmented, softcap=softcap),
        grid=(b * h_kv, s_kv // block_kv, groups, dkv_inner),
        in_specs=[dkv_q_spec, dkv_kv_spec, dkv_kv_spec, dkv_q_spec,
                  dkv_q_spec, dkv_stat_spec, dkv_seg_q_spec,
                  dkv_seg_kv_spec],
        out_specs=[
            pl.BlockSpec((1, block_kv, d),
                         lambda bh, j, g, i: (bh, j, 0)),
            pl.BlockSpec((1, block_kv, d),
                         lambda bh, j, g, i: (bh, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h_kv, s_kv, d), k.dtype),
            jax.ShapeDtypeStruct((b * h_kv, s_kv, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_kv, d), jnp.float32),
            pltpu.VMEM((block_kv, d), jnp.float32),
        ],
        interpret=_should_interpret(),
    )(qr, kr, vr, outr, dor, lse, seg_q, seg_kv)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_kv=block_kv,
                          window=window, num_kv_total=num_kv_total,
                          segmented=segmented, softcap=softcap),
        grid=(b * h, s // block_q, dq_inner),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, q_spec, stat_spec,
                  dq_seg_q_spec, dq_seg_kv_spec],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
        ],
        out_shape=[jax.ShapeDtypeStruct((b * h, s, d), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_should_interpret(),
    )(qr, kr, vr, outr, dor, lse, seg_q, seg_kv)[0]

    return (dq.reshape(b, h, s, d), dk.reshape(b, h_kv, s_kv, d),
            dv.reshape(b, h_kv, s_kv, d), None)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9))
def _flash_bhsd(q, k, v, segment_ids, causal, block_q, block_kv, window,
                softcap, scale_override):
    out, _ = _flash_fwd(q, k, v, segment_ids, causal=causal,
                        block_q=block_q, block_kv=block_kv,
                        window=window, softcap=softcap,
                        scale_override=scale_override)
    return out


def _flash_bhsd_fwd(q, k, v, segment_ids, causal, block_q, block_kv,
                    window, softcap, scale_override):
    out, lse = _flash_fwd(q, k, v, segment_ids, causal=causal,
                          block_q=block_q, block_kv=block_kv,
                          window=window, softcap=softcap,
                          scale_override=scale_override)
    return out, (q, k, v, segment_ids, out, lse)


def _flash_bhsd_bwd(causal, block_q, block_kv, window, softcap,
                    scale_override, residuals, dout):
    # 4-tuple (dq, dk, dv, None): segment ids are integral, their
    # cotangent is symbolically zero.
    return _bwd_flash(residuals, dout, causal=causal, block_q=block_q,
                      block_kv=block_kv, window=window, softcap=softcap,
                      scale_override=scale_override)


_flash_bhsd.defvjp(_flash_bhsd_fwd, _flash_bhsd_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_kv: int = DEFAULT_BLOCK_KV,
                    window=None, segment_ids=None,
                    logit_softcap=None, scale=None) -> jax.Array:
    """Flash attention; q [B,S,H,D], k/v [B,S,Hkv,D] (GQA) → [B,S,H,D].

    window: Mistral-style sliding window — out-of-window blocks are
    skipped entirely, so work scales O(S·W) instead of O(S²).
    segment_ids [B, S] int: packed-sequence document masking — queries
    attend only within their own segment. Costs one [bq,1]==[1,bkv]
    compare per live block; no O(S²) mask ever materializes, which is
    the whole point vs the XLA fallback at long sequence."""
    b, s, h, d = q.shape
    h_kv = k.shape[2]
    assert h % h_kv == 0, (h, h_kv)
    # K/V stay at Hkv width; the kernels' index_maps resolve the shared
    # KV head (bh // groups), so GQA reads each K/V block once per
    # group instead of once per query head.
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    out = _flash_bhsd(qt, kt, vt, segment_ids, causal, block_q,
                      block_kv, window, logit_softcap, scale)
    return jnp.transpose(out, (0, 2, 1, 3))
