"""Azure VM provisioner op-set (lean twin of sky/provision/azure/instance.py).

Dispatched by provider name 'azure'. The cluster boundary is a dedicated
resource group ``xsky-<cluster>-rg`` — the Azure-idiomatic version of the
tag-tracking the AWS/GCP providers use: every resource (VNet, NICs,
public IPs, VMs, disks) lives in it, so teardown is one resource-group
delete and there is nothing to leak. VMs carry the same
``xsky-cluster`` / ``xsky-head`` / ``xsky-node-index`` tags as the other
providers so shared code can stay provider-agnostic.

Spot capacity uses VM ``priority: Spot`` with Deallocate eviction.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common
from skypilot_tpu.provision.azure import rest

logger = sky_logging.init_logger(__name__)

CLUSTER_TAG = 'xsky-cluster'
HEAD_TAG = 'xsky-head'
NODE_INDEX_TAG = 'xsky-node-index'

DEFAULT_IMAGE = {
    'publisher': 'Canonical',
    'offer': '0001-com-ubuntu-server-jammy',
    'sku': '22_04-lts-gen2',
    'version': 'latest',
}

# Pluggable transport for tests (scripted fake ARM).
_transport_factory = rest.Transport


def set_transport_factory(factory) -> None:
    global _transport_factory
    _transport_factory = factory


def _rg(cluster_name: str, region: str) -> str:
    """Region-scoped: ARM forbids changing an existing resource group's
    location, so a failover retry in another region must not collide
    with the (possibly still async-deleting) group from the failed
    attempt."""
    return f'xsky-{cluster_name}-{region}-rg'


def _transport(provider_config: Dict[str, Any]) -> rest.Transport:
    region = provider_config.get('region')
    if not region:
        raise exceptions.InvalidSkyTpuConfigError(
            'Azure provider_config requires region.')
    return _transport_factory(region)


_POWER_MAP = {
    'PowerState/starting': 'PENDING',
    'PowerState/running': 'RUNNING',
    'PowerState/stopping': 'STOPPING',
    'PowerState/stopped': 'STOPPING',       # OS stopped, still billed
    'PowerState/deallocating': 'STOPPING',
    'PowerState/deallocated': 'STOPPED',
}


def _power_state(vm: Dict[str, Any]) -> str:
    view = vm.get('properties', {}).get('instanceView', {})
    for status in view.get('statuses', []):
        code = status.get('code', '')
        if code.startswith('PowerState/'):
            return _POWER_MAP.get(code, 'PENDING')
    return 'PENDING'


def _compute_path(t: rest.Transport, cluster_name: str,
                  suffix: str = '') -> str:
    return (f'/resourceGroups/{_rg(cluster_name, t.region)}/providers'
            f'/Microsoft.Compute{suffix}')


def _network_path(t: rest.Transport, cluster_name: str,
                  suffix: str = '') -> str:
    return (f'/resourceGroups/{_rg(cluster_name, t.region)}/providers'
            f'/Microsoft.Network{suffix}')


def _list_vms(t: rest.Transport, cluster_name: str,
              expand_view: bool = True) -> List[Dict[str, Any]]:
    suffix = '/virtualMachines'
    if expand_view:
        suffix += '?$expand=instanceView'
    try:
        reply = t.call('GET', _compute_path(t, cluster_name, suffix))
    except rest.AzureApiError as e:
        if e.code in ('NotFound', 'ResourceGroupNotFound'):
            return []
        raise
    return list(reply.get('value', []))


def _sorted_nodes(vms: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    def key(vm):
        idx = (vm.get('tags') or {}).get(NODE_INDEX_TAG, '')
        return (int(idx) if idx.isdigit() else 10**6, vm.get('name', ''))
    return sorted(vms, key=key)


def _ensure_network(t: rest.Transport, cluster_name: str,
                    region: str) -> str:
    """Resource group + NSG + VNet/subnet; returns the subnet id.

    Standard-SKU public IPs deny ALL inbound until an NSG allows it, so
    the subnet gets a cluster NSG with an SSH allow rule up front —
    without it every post-provision lifecycle op (setup/run/rsync)
    would time out on port 22. open_ports() appends rules to the same
    NSG.
    """
    t.call('PUT', f'/resourceGroups/{_rg(cluster_name, region)}',
           {'location': region, 'tags': {CLUSTER_TAG: cluster_name}})
    nsg_path = _network_path(t, cluster_name,
                             f'/networkSecurityGroups/{cluster_name}-nsg')
    t.call('PUT', nsg_path, {
        'location': region,
        'properties': {
            'securityRules': [{
                'name': 'xsky-ssh',
                'properties': {
                    'priority': 1000, 'direction': 'Inbound',
                    'access': 'Allow', 'protocol': 'Tcp',
                    'sourceAddressPrefix': '*', 'sourcePortRange': '*',
                    'destinationAddressPrefix': '*',
                    'destinationPortRange': '22',
                },
            }],
        },
    })
    nsg_id = t.wait_provisioned(nsg_path).get('id', nsg_path)
    vnet_path = _network_path(t, cluster_name,
                              f'/virtualNetworks/{cluster_name}-vnet')
    t.call('PUT', vnet_path, {
        'location': region,
        'properties': {
            'addressSpace': {'addressPrefixes': ['10.40.0.0/16']},
            'subnets': [{
                'name': 'default',
                'properties': {
                    'addressPrefix': '10.40.0.0/20',
                    'networkSecurityGroup': {'id': nsg_id},
                },
            }],
        },
    })
    vnet = t.wait_provisioned(vnet_path)
    subnets = vnet.get('properties', {}).get('subnets', [])
    if subnets and subnets[0].get('id'):
        return subnets[0]['id']
    # NIC bodies need the full ARM id (the relative path only works for
    # our own transport calls).
    return (f'/subscriptions/{t.subscription}{vnet_path}/subnets/default')


def _create_node(t: rest.Transport, cluster_name: str, region: str,
                 subnet_id: str, index: int, is_head: bool,
                 node_cfg: Dict[str, Any]) -> str:
    name = f'{cluster_name}-{index}'
    ip_path = _network_path(t, cluster_name, f'/publicIPAddresses/{name}-ip')
    t.call('PUT', ip_path, {
        'location': region,
        'sku': {'name': 'Standard'},
        'properties': {'publicIPAllocationMethod': 'Static'},
    })
    ip_id = t.wait_provisioned(ip_path).get('id', ip_path)
    nic_path = _network_path(t, cluster_name,
                             f'/networkInterfaces/{name}-nic')
    t.call('PUT', nic_path, {
        'location': region,
        'properties': {
            'ipConfigurations': [{
                'name': 'primary',
                'properties': {
                    'subnet': {'id': subnet_id},
                    'publicIPAddress': {'id': ip_id},
                },
            }],
        },
    })
    nic_id = t.wait_provisioned(nic_path).get('id', nic_path)

    tags = {CLUSTER_TAG: cluster_name, NODE_INDEX_TAG: str(index)}
    if is_head:
        tags[HEAD_TAG] = 'true'
    image = node_cfg.get('image_id')
    image_ref = ({'id': image} if image and image.startswith('/')
                 else DEFAULT_IMAGE if not image else
                 dict(zip(('publisher', 'offer', 'sku', 'version'),
                          image.split(':'))))
    body: Dict[str, Any] = {
        'location': region,
        'tags': tags,
        'properties': {
            'hardwareProfile': {'vmSize': node_cfg['instance_type']},
            'storageProfile': {
                'imageReference': image_ref,
                'osDisk': {
                    'createOption': 'FromImage',
                    'diskSizeGB': int(node_cfg.get('disk_size') or 256),
                    'managedDisk': {
                        'storageAccountType': 'Premium_LRS'},
                },
            },
            'osProfile': {
                'computerName': name,
                'adminUsername': node_cfg.get('ssh_user', 'azureuser'),
                'linuxConfiguration': {
                    'disablePasswordAuthentication': True,
                    'ssh': {'publicKeys': [{
                        'path': ('/home/'
                                 f'{node_cfg.get("ssh_user", "azureuser")}'
                                 '/.ssh/authorized_keys'),
                        'keyData': node_cfg.get('ssh_public_key', ''),
                    }]},
                },
            },
            # deleteOption cascades: deleting the VM also deletes its
            # OS disk and NIC server-side, so partial-attempt cleanup
            # and teardown cannot leak billed resources.
            'networkProfile': {'networkInterfaces': [{
                'id': nic_id,
                'properties': {'deleteOption': 'Delete'},
            }]},
        },
    }
    body['properties']['storageProfile']['osDisk'][
        'deleteOption'] = 'Delete'
    if node_cfg.get('use_spot'):
        body['properties']['priority'] = 'Spot'
        body['properties']['evictionPolicy'] = 'Deallocate'
        body['properties']['billingProfile'] = {'maxPrice': -1}
    t.call('PUT', _compute_path(t, cluster_name, f'/virtualMachines/{name}'),
           body)
    return name


def run_instances(region: str, zone: Optional[str], cluster_name: str,
                  config: common.ProvisionConfig) -> common.ProvisionRecord:
    node_cfg = config.node_config
    t = _transport(config.provider_config)
    created: List[str] = []
    attempted: List[str] = []
    resumed: List[str] = []
    existing: List[Dict[str, Any]] = []
    touched_network = False
    try:
        existing = _sorted_nodes(_list_vms(t, cluster_name))
        if config.resume_stopped_nodes:
            for vm in existing:
                if _power_state(vm) == 'STOPPED':
                    t.call('POST', _compute_path(
                        t, cluster_name,
                        f'/virtualMachines/{vm["name"]}/start'))
                    resumed.append(vm['name'])
        have = len(existing)
        missing = config.count - have
        if missing > 0:
            touched_network = True
            subnet_id = _ensure_network(t, cluster_name, region)
            has_head = any((vm.get('tags') or {}).get(HEAD_TAG) == 'true'
                           for vm in existing)
            for node in range(missing):
                # Record the attempt BEFORE creating: a failure partway
                # through _create_node (IP/NIC made, VM refused) must
                # still be cleaned up below.
                attempted.append(f'{cluster_name}-{have + node}')
                _create_node(
                    t, cluster_name, region, subnet_id,
                    index=have + node,
                    is_head=(not has_head and node == 0),
                    node_cfg=node_cfg)
                created.append(attempted[-1])
            # VM PUT is an LRO: surface allocation failures (capacity)
            # here, inside the failover-classified scope.
            for name in created:
                t.wait_provisioned(_compute_path(
                    t, cluster_name, f'/virtualMachines/{name}'))
    except rest.AzureApiError as e:
        # Partial gang cleanup. Fresh cluster: the (region-scoped)
        # resource group is this attempt's whole blast radius — delete
        # it even if the failure hit before any VM (half-built network
        # would otherwise linger), so the failover retry starts from
        # zero. Scale-up/resume of an existing cluster: only this
        # attempt's VMs may go (their disk/NIC cascade via
        # deleteOption); the healthy fleet and its network survive.
        try:
            # Fresh-cluster delete only if this attempt actually began
            # building (a transient error on the initial listing of a
            # HEALTHY cluster must never nuke its resource group).
            if not existing and touched_network:
                t.call('DELETE',
                       f'/resourceGroups/{_rg(cluster_name, region)}'
                       '?forceDeletionTypes='
                       'Microsoft.Compute/virtualMachines')
            else:
                for name in attempted:
                    # VM delete cascades NIC/disk via deleteOption; a
                    # node that failed before its VM existed still has
                    # an orphan NIC/IP. All best-effort (404 for the
                    # never-created, 409 while detaching — the next
                    # terminate retries).
                    for path in (
                            _compute_path(t, cluster_name,
                                          f'/virtualMachines/{name}'),
                            _network_path(t, cluster_name,
                                          f'/networkInterfaces/{name}-nic'),
                            _network_path(t, cluster_name,
                                          f'/publicIPAddresses/{name}-ip')):
                        try:
                            t.call('DELETE', path)
                        except rest.AzureApiError:
                            pass
        except rest.AzureApiError as cleanup_err:
            logger.warning(
                f'Cleanup of partial attempt failed: {cleanup_err}')
        raise rest.classify_error(e, zone or region) from e
    head = None
    for vm in _sorted_nodes(_list_vms(t, cluster_name,
                                      expand_view=False)):
        if (vm.get('tags') or {}).get(HEAD_TAG) == 'true':
            head = vm['name']
            break
    return common.ProvisionRecord(
        provider_name='azure', cluster_name=cluster_name, region=region,
        zone=zone, resumed_instance_ids=resumed,
        created_instance_ids=created, head_instance_id=head)


def wait_instances(region: str, cluster_name: str, state: str,
                   provider_config: Optional[Dict[str, Any]] = None,
                   timeout_s: float = 600.0,
                   poll_interval_s: float = 5.0) -> None:
    t = _transport(provider_config or {'region': region})
    deadline = time.time() + timeout_s
    # ARM list calls can return a stale empty page right after create
    # (create-vs-list visibility race): poll until the baseline set is
    # non-empty instead of either raising on one stale read or burning
    # the whole timeout against an `all(...)` that can never succeed.
    expected = {vm['name'] for vm in _list_vms(t, cluster_name,
                                               expand_view=False)}
    while not expected and time.time() < deadline:
        time.sleep(poll_interval_s)
        expected = {vm['name'] for vm in _list_vms(t, cluster_name,
                                                   expand_view=False)}
    if not expected:
        raise exceptions.ProvisionError(
            f'Cluster {cluster_name!r} has no VMs to wait on (resource '
            'group empty or never became visible).')
    while time.time() < deadline:
        vms = _list_vms(t, cluster_name)
        alive = {vm['name'] for vm in vms}
        lost = expected - alive
        if lost:
            raise exceptions.CapacityError(
                f'VM(s) {sorted(lost)} disappeared while waiting for '
                f'{state} (spot eviction during boot?).')
        if vms and all(_power_state(vm) == state for vm in vms):
            return
        time.sleep(poll_interval_s)
    raise exceptions.ProvisionError(
        f'Cluster {cluster_name!r} did not reach {state} within '
        f'{timeout_s}s.')


def stop_instances(cluster_name: str,
                   provider_config: Dict[str, Any]) -> None:
    t = _transport(provider_config)
    for vm in _list_vms(t, cluster_name):
        if _power_state(vm) in ('PENDING', 'RUNNING'):
            t.call('POST', _compute_path(
                t, cluster_name,
                f'/virtualMachines/{vm["name"]}/deallocate'))


def terminate_instances(cluster_name: str,
                        provider_config: Dict[str, Any]) -> None:
    t = _transport(provider_config)
    try:
        t.call('DELETE',
               f'/resourceGroups/{_rg(cluster_name, t.region)}'
               '?forceDeletionTypes=Microsoft.Compute/virtualMachines')
    except rest.AzureApiError as e:
        if e.code not in ('NotFound', 'ResourceGroupNotFound'):
            raise


def query_instances(cluster_name: str, provider_config: Dict[str, Any]
                    ) -> Dict[str, Optional[str]]:
    t = _transport(provider_config)
    # Terminated nodes are gone from the listing (the resource group is
    # the blast radius), so every listed VM has a live status.
    return {vm['name']: _power_state(vm)
            for vm in _list_vms(t, cluster_name)}


def _nic_ips(t: rest.Transport, cluster_name: str,
             vm: Dict[str, Any]) -> Dict[str, Optional[str]]:
    """{internal, external} for the VM's primary NIC."""
    nics = vm.get('properties', {}).get('networkProfile', {}).get(
        'networkInterfaces', [])
    if not nics:
        return {'internal': '', 'external': None}
    nic_id = nics[0].get('id', '')
    nic_name = nic_id.rsplit('/', 1)[-1]
    nic = t.call('GET', _network_path(
        t, cluster_name, f'/networkInterfaces/{nic_name}'))
    internal, external = '', None
    for ipcfg in nic.get('properties', {}).get('ipConfigurations', []):
        props = ipcfg.get('properties', {})
        internal = props.get('privateIPAddress', internal)
        pub = props.get('publicIPAddress', {})
        if pub.get('id'):
            ip_name = pub['id'].rsplit('/', 1)[-1]
            ip = t.call('GET', _network_path(
                t, cluster_name, f'/publicIPAddresses/{ip_name}'))
            external = ip.get('properties', {}).get('ipAddress', external)
    return {'internal': internal, 'external': external}


def get_cluster_info(region: str, cluster_name: str,
                     provider_config: Dict[str, Any]) -> common.ClusterInfo:
    del region
    t = _transport(provider_config)
    vms = _sorted_nodes(_list_vms(t, cluster_name))
    if not vms:
        raise exceptions.ClusterDoesNotExist(cluster_name)
    instances: Dict[str, common.InstanceInfo] = {}
    head_id: Optional[str] = None
    for vm in vms:
        tags = dict(vm.get('tags') or {})
        ips = _nic_ips(t, cluster_name, vm)
        info = common.InstanceInfo(
            instance_id=vm['name'],
            internal_ip=ips['internal'],
            external_ip=ips['external'],
            status=_power_state(vm) or 'PENDING',
            tags=tags,
        )
        instances[info.instance_id] = info
        if tags.get(HEAD_TAG) == 'true' and head_id is None:
            head_id = info.instance_id
    if head_id is None:
        head_id = sorted(instances)[0]
    return common.ClusterInfo(
        instances=instances, head_instance_id=head_id,
        provider_name='azure',
        provider_config=dict(provider_config or {}),
        ssh_user=provider_config.get('ssh_user', 'azureuser'))


def open_ports(cluster_name: str, ports: List[str],
               provider_config: Dict[str, Any]) -> None:
    """Append allow rules to the cluster NSG created at provision time
    (Standard public IPs deny inbound by default)."""
    t = _transport(provider_config)
    nsg = f'/networkSecurityGroups/{cluster_name}-nsg'
    # Priorities must be unique per NSG/direction across *all* calls:
    # read the live rule set and allocate from the first free slot.
    try:
        current = t.call('GET', _network_path(t, cluster_name, nsg))
    except rest.AzureApiError as e:
        logger.warning(f'open_ports: cannot read NSG: {e}')
        return
    rules = current.get('properties', {}).get('securityRules', [])
    used = {r.get('properties', {}).get('priority') for r in rules}
    have = {r.get('name') for r in rules}
    next_priority = 1100
    for port in ports:
        lo, _, hi = str(port).partition('-')
        # The range's upper bound is part of the identity: '8080' and
        # '8080-8090' must not collapse to one rule name, or the wider
        # range is silently skipped as already-open.
        name = f'xsky-port-{lo}-{hi}' if hi else f'xsky-port-{lo}'
        if name in have:
            continue
        while next_priority in used:
            next_priority += 1
        rule = f'{nsg}/securityRules/{name}'
        try:
            t.call('PUT', _network_path(t, cluster_name, rule), {
                'properties': {
                    'priority': next_priority,
                    'direction': 'Inbound', 'access': 'Allow',
                    'protocol': 'Tcp',
                    'sourceAddressPrefix': '*', 'sourcePortRange': '*',
                    'destinationAddressPrefix': '*',
                    'destinationPortRange': f'{lo}-{hi}' if hi else lo,
                },
            })
            used.add(next_priority)
        except rest.AzureApiError as e:
            logger.warning(f'open_ports({port}) failed: {e}')


def cleanup_ports(cluster_name: str,
                  provider_config: Dict[str, Any]) -> None:
    del cluster_name, provider_config  # resource-group delete covers it
