"""Generate the DigitalOcean catalog CSV (twin of
sky/catalog/data_fetchers/fetch_do.py in role).

With a token + egress, rows come from GET /v2/sizes (price_hourly per
size); offline the checked-in CSV is a static snapshot of the GPU
droplet sizes + common CPU sizes. No spot market (SpotPrice 0).

Run: python -m skypilot_tpu.catalog.data_fetchers.fetch_do
"""
from __future__ import annotations

import csv
import os
from typing import List, Tuple

# (size, acc_name, acc_count, vcpus, mem_gib, acc_mem_gib, price)
_SKUS: List[Tuple[str, str, float, float, float, float, float]] = [
    ('gpu-h100x1-80gb', 'H100', 1, 20, 240, 80, 3.39),
    ('gpu-h100x8-640gb', 'H100', 8, 160, 1920, 640, 23.92),
    ('gpu-l40sx1-48gb', 'L40S', 1, 8, 64, 48, 1.57),
    ('gpu-mi300x1-192gb', 'MI300X', 1, 20, 240, 192, 1.99),
    ('gpu-mi300x8-1536gb', 'MI300X', 8, 160, 1920, 1536, 15.92),
    ('gpu-4000adax1-20gb', 'RTX4000-Ada', 1, 8, 32, 20, 0.76),
    ('gpu-6000adax1-48gb', 'RTX6000-Ada', 1, 8, 64, 48, 1.57),
    ('s-4vcpu-8gb', '', 0, 4, 8, 0, 0.071),
    ('s-8vcpu-16gb', '', 0, 8, 16, 0, 0.143),
    ('c-16', '', 0, 16, 32, 0, 0.381),
]

# GPU droplets live in the AI/ML data centers.
_GPU_REGIONS = ['nyc2', 'tor1', 'atl1']
_CPU_REGIONS = ['nyc1', 'nyc3', 'sfo3', 'ams3', 'fra1', 'sgp1']

HEADER = ['InstanceType', 'AcceleratorName', 'AcceleratorCount', 'vCPUs',
          'MemoryGiB', 'AcceleratorMemoryGiB', 'Price', 'SpotPrice',
          'Region', 'AvailabilityZone']


def rows_static() -> List[List[str]]:
    out = []
    for itype, acc, count, vcpus, mem, acc_mem, price in _SKUS:
        regions = _GPU_REGIONS if acc else _CPU_REGIONS
        for region in regions:
            out.append([itype, acc, f'{count:g}', f'{vcpus:g}',
                        f'{mem:g}', f'{acc_mem:g}', f'{price:.4f}', '0',
                        region, region])
    return out


def main() -> None:
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, 'data', 'do', 'catalog.csv')
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, 'w', newline='', encoding='utf-8') as f:
        writer = csv.writer(f)
        writer.writerow(HEADER)
        writer.writerows(rows_static())
    print(f'Wrote {path} (static snapshot)')


if __name__ == '__main__':
    main()
