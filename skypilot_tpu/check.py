"""Credential probing / enabled-cloud gating (twin of sky/check.py:53).

`get_cached_enabled_clouds` is the single source the optimizer consults.
The Fake cloud (tests/demos) is only enabled when XSKY_ENABLE_FAKE_CLOUD=1
so it never shadows real clouds in normal use.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Tuple

from skypilot_tpu import clouds as _clouds  # registers clouds  # noqa: F401
from skypilot_tpu import exceptions
from skypilot_tpu.utils import registry

_lock = threading.Lock()
_cached: Optional[List[str]] = None


def _fake_cloud_enabled() -> bool:
    return os.environ.get('XSKY_ENABLE_FAKE_CLOUD', '0') == '1'


def check_capabilities(
        quiet: bool = False) -> Dict[str, Tuple[bool, Optional[str]]]:
    """Probe every registered cloud's credentials."""
    results: Dict[str, Tuple[bool, Optional[str]]] = {}
    for cloud in registry.CLOUD_REGISTRY.values():
        if cloud.name == 'fake' and not _fake_cloud_enabled():
            results[cloud.name] = (False, 'fake cloud disabled '
                                   '(set XSKY_ENABLE_FAKE_CLOUD=1)')
            continue
        try:
            ok, reason = cloud.check_credentials()
        except Exception as e:  # pylint: disable=broad-except
            ok, reason = False, str(e)
        results[cloud.name] = (ok, reason)
    return results


def refresh_enabled_clouds() -> List[str]:
    global _cached
    with _lock:
        _cached = [name for name, (ok, _) in check_capabilities().items()
                   if ok]
        return list(_cached)


def get_cached_enabled_clouds() -> List[str]:
    if _cached is None:
        return refresh_enabled_clouds()
    return list(_cached)


def get_cached_enabled_clouds_or_refresh(
        raise_if_no_cloud_access: bool = False) -> List[str]:
    clouds = get_cached_enabled_clouds()
    if not clouds:
        clouds = refresh_enabled_clouds()
    if raise_if_no_cloud_access and not clouds:
        raise exceptions.NoCloudAccessError(
            'No cloud is enabled. Configure credentials and run `xsky check`.')
    return clouds


def set_enabled_clouds_for_test(clouds: Optional[List[str]]) -> None:
    global _cached
    with _lock:
        _cached = list(clouds) if clouds is not None else None
