"""Fluidstack REST transport (api-key header, no SDK).

Role twin of sky/provision/fluidstack/fluidstack_utils.py, on this
repo's transport pattern. Key from $FLUIDSTACK_API_KEY or
~/.fluidstack/api_key (the same path the reference reads).
"""
from __future__ import annotations

import json
import os
import time
import urllib.error
import urllib.request
from typing import Any, Dict, Optional

from skypilot_tpu import exceptions

API_ENDPOINT = 'https://platform.fluidstack.io'
CREDENTIALS_PATH = '~/.fluidstack/api_key'
_MAX_ATTEMPTS = 4
_BACKOFF_S = 2.0


class FluidstackApiError(Exception):

    def __init__(self, status: int, message: str) -> None:
        super().__init__(f'{status}: {message}')
        self.status = status
        self.message = message


def load_api_key() -> Optional[str]:
    key = os.environ.get('FLUIDSTACK_API_KEY')
    if key:
        return key
    path = os.path.expanduser(CREDENTIALS_PATH)
    if not os.path.exists(path):
        return None
    try:
        with open(path, encoding='utf-8') as f:
            return f.read().strip() or None
    except OSError:
        return None


def classify_error(e: FluidstackApiError,
                   region: Optional[str] = None) -> Exception:
    text = e.message.lower()
    where = f' in {region}' if region else ''
    if 'no capacity' in text or 'out of stock' in text or \
            'unavailable' in text:
        return exceptions.CapacityError(f'Fluidstack capacity{where}: {e}')
    if 'quota' in text or 'limit' in text:
        return exceptions.QuotaExceededError(f'Fluidstack quota{where}: {e}')
    if e.status in (401, 403):
        return exceptions.PermissionError_(f'Fluidstack auth: {e}')
    if e.status in (400, 422):
        return exceptions.InvalidRequestError(f'Fluidstack request: {e}')
    return exceptions.ProvisionError(f'Fluidstack API{where}: {e}')


class Transport:

    def __init__(self, api_key: Optional[str] = None) -> None:
        key = api_key or load_api_key()
        if not key:
            raise exceptions.PermissionError_(
                'Fluidstack API key not found (set $FLUIDSTACK_API_KEY '
                f'or populate {CREDENTIALS_PATH}).')
        self._key = key

    def call(self, method: str, path: str,
             body: Optional[Dict[str, Any]] = None) -> Any:
        url = f'{API_ENDPOINT}{path}'
        data = json.dumps(body).encode() if body is not None else None
        for attempt in range(_MAX_ATTEMPTS):
            req = urllib.request.Request(
                url, data=data, method=method,
                headers={'api-key': self._key,
                         'Content-Type': 'application/json'})
            try:
                with urllib.request.urlopen(req, timeout=60) as resp:
                    payload = resp.read()
                    return json.loads(payload) if payload else {}
            except urllib.error.HTTPError as e:
                if e.code == 429 and attempt < _MAX_ATTEMPTS - 1:
                    time.sleep(_BACKOFF_S * (attempt + 1))
                    continue
                try:
                    err = json.loads(e.read() or b'{}')
                    message = err.get('message') or err.get(
                        'detail') or str(e)
                    raise FluidstackApiError(e.code, str(message))
                except (ValueError, AttributeError):
                    raise FluidstackApiError(e.code, str(e)) from e
            except urllib.error.URLError as e:
                raise exceptions.ProvisionError(
                    f'Fluidstack API unreachable: {e}') from e
        # Unreachable: every iteration returns or raises.
