"""Generate the Cudo Compute catalog CSV (twin of
sky/catalog/data_fetchers/fetch_cudo.py in role).

Instance type grammar `<machine_type>_<gpus>x<GPU>` mirrors the
reference's cudo_machine_type mapping; data centers are the regions.
Static published on-demand prices. No spot market.

Run: python -m skypilot_tpu.catalog.data_fetchers.fetch_cudo
"""
from __future__ import annotations

import csv
import os
from typing import List, Tuple

# (itype, acc, count, vcpus, mem_gib, acc_mem_gib, price)
_SKUS: List[Tuple[str, str, float, float, float, float, float]] = [
    ('epyc-milan-rtx-a4000_1xRTXA4000', 'RTXA4000', 1, 4, 16, 16, 0.35),
    ('epyc-milan-rtx-a4000_2xRTXA4000', 'RTXA4000', 2, 8, 32, 32, 0.70),
    ('epyc-rome-rtx-a5000_1xRTXA5000', 'RTXA5000', 1, 4, 16, 24, 0.52),
    ('epyc-rome-rtx-a5000_2xRTXA5000', 'RTXA5000', 2, 8, 32, 48, 1.04),
    ('epyc-milan-rtx-a6000_1xRTXA6000', 'RTXA6000', 1, 8, 32, 48, 1.00),
    ('epyc-milan-rtx-a6000_4xRTXA6000', 'RTXA6000', 4, 32, 128, 192,
     4.00),
    ('intel-broadwell-a40_1xA40', 'A40', 1, 8, 32, 48, 1.12),
    ('epyc-milan-v100_1xV100', 'V100', 1, 8, 32, 16, 0.87),
    ('epyc-genoa-h100_1xH100', 'H100', 1, 24, 120, 80, 2.79),
    ('epyc-genoa-h100_8xH100', 'H100', 8, 192, 960, 640, 22.32),
    ('epyc-milan_0x_cpu4', '', 0, 4, 16, 0, 0.12),
    ('epyc-milan_0x_cpu8', '', 0, 8, 32, 0, 0.24),
]

_REGIONS = ['gb-bournemouth-1', 'no-luster-1', 'se-smedjebacken-1',
            'us-newyork-1', 'us-santaclara-1']

HEADER = ['InstanceType', 'AcceleratorName', 'AcceleratorCount', 'vCPUs',
          'MemoryGiB', 'AcceleratorMemoryGiB', 'Price', 'SpotPrice',
          'Region', 'AvailabilityZone']


def rows_static() -> List[List[str]]:
    out = []
    for itype, acc, count, vcpus, mem, acc_mem, price in _SKUS:
        for region in _REGIONS:
            out.append([itype, acc, f'{count:g}', f'{vcpus:g}',
                        f'{mem:g}', f'{acc_mem:g}', f'{price:.4f}', '0',
                        region, region])
    return out


def main() -> None:
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(here, 'data', 'cudo', 'catalog.csv')
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, 'w', newline='', encoding='utf-8') as f:
        writer = csv.writer(f)
        writer.writerow(HEADER)
        writer.writerows(rows_static())
    print(f'Wrote {path} (static snapshot)')


if __name__ == '__main__':
    main()
