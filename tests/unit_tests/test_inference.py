"""Inference engine tests (tiny model, virtual CPU mesh).

The load-bearing check: greedy decode through the slot KV cache must
reproduce token-by-token full-forward greedy decoding exactly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.infer import engine as engine_lib
from skypilot_tpu.infer import orchestrator as orch_lib
from skypilot_tpu.infer import sampling as sampling_lib
from skypilot_tpu.models import llama
from skypilot_tpu.parallel import mesh as mesh_lib


pytestmark = pytest.mark.slow  # heavy tier: subprocess e2e / jit compiles


@pytest.fixture(scope='module')
def tiny_engine():
    config = engine_lib.EngineConfig(
        model=llama.LLAMA_TINY,
        max_slots=4,
        max_target_len=64,
        prefill_buckets=(16, 32),
    )
    params = llama.init(llama.LLAMA_TINY, jax.random.PRNGKey(0))
    return engine_lib.InferenceEngine(config, params)


def _reference_greedy(params, prompt, n_new):
    """Greedy decode by full re-forward each step (no cache)."""
    c = llama.LLAMA_TINY
    tokens = list(prompt)
    for _ in range(n_new):
        logits = llama.forward(c, params,
                               jnp.asarray([tokens], jnp.int32))
        tokens.append(int(jnp.argmax(logits[0, -1])))
    return tokens[len(prompt):]


def test_cached_decode_matches_full_forward(tiny_engine):
    prompt = [5, 17, 3, 99, 42]
    n_new = 8
    expected = _reference_greedy(tiny_engine.params, prompt, n_new)

    orch = orch_lib.Orchestrator(tiny_engine)
    outputs = orch.generate([prompt], max_new_tokens=n_new)
    assert outputs[0] == expected


def test_continuous_batching_multiple_requests(tiny_engine):
    prompts = [[1, 2, 3], [7, 8, 9, 10, 11], [20, 21], [4] * 12,
               [13, 14, 15], [5, 6]]
    n_new = 6
    expected = [_reference_greedy(tiny_engine.params, p, n_new)
                for p in prompts]
    orch = orch_lib.Orchestrator(tiny_engine)
    outputs = orch.generate(prompts, max_new_tokens=n_new)
    # 6 requests > 4 slots → at least one admission wave after a release.
    assert outputs == expected


def test_eos_stops_generation(tiny_engine):
    prompt = [5, 17, 3]
    full = _reference_greedy(tiny_engine.params, prompt, 10)
    eos = full[3]  # pretend the 4th generated token is EOS
    orch = orch_lib.Orchestrator(tiny_engine)
    outputs = orch.generate([prompt], max_new_tokens=10, eos_token_id=eos)
    assert outputs[0] == full[:3]


def test_prefill_bucket_selection(tiny_engine):
    assert tiny_engine.bucket_for(3) == 16
    assert tiny_engine.bucket_for(16) == 16
    assert tiny_engine.bucket_for(17) == 32
    with pytest.raises(ValueError):
        tiny_engine.bucket_for(64)


@pytest.mark.parametrize('decode_impl', ['xla', 'kernel'])
def test_sharded_engine_on_mesh(decode_impl, monkeypatch):
    """Engine over a 8-device mesh with tensor parallelism compiles+runs.

    Token-for-token equality with the unsharded engine is asserted only
    for the XLA decode path: TP splits the prefill projections, whose
    bf16 reduction-order differences leave ~1e-4 logit gaps on
    LLAMA_TINY's random params where a tie legitimately flips the
    greedy argmax — so for the Pallas-kernel path the pin is
    valid-and-deterministic generation (its numeric parity vs the XLA
    reference, including the shard_map island, is pinned with
    tolerances in test_decode_attention.py)."""
    if decode_impl == 'xla':
        monkeypatch.setenv('XSKY_DECODE_ATTN', 'xla')
    mesh = mesh_lib.build_mesh(mesh_lib.MeshPlan(data=4, tensor=2))
    config = engine_lib.EngineConfig(
        model=llama.LLAMA_TINY, max_slots=4, max_target_len=32,
        prefill_buckets=(16,))
    params = llama.init(llama.LLAMA_TINY, jax.random.PRNGKey(0))
    engine = engine_lib.InferenceEngine(config, params, mesh=mesh)
    prompt = [3, 1, 4, 1, 5]
    out_sharded = orch_lib.Orchestrator(engine).generate(
        [prompt], max_new_tokens=5)
    assert len(out_sharded[0]) == 5
    assert all(0 <= t < llama.LLAMA_TINY.vocab_size
               for t in out_sharded[0])
    if decode_impl == 'xla':
        reference = engine_lib.InferenceEngine(config, params)
        out_ref = orch_lib.Orchestrator(reference).generate(
            [prompt], max_new_tokens=5)
        assert out_sharded == out_ref
    else:
        engine2 = engine_lib.InferenceEngine(config, params, mesh=mesh)
        out_again = orch_lib.Orchestrator(engine2).generate(
            [prompt], max_new_tokens=5)
        assert out_sharded == out_again


def test_sampling_topk_topp():
    logits = jnp.asarray([[1.0, 2.0, 3.0, 4.0]])
    key = jax.random.PRNGKey(0)
    # top_k=1 → deterministic argmax even with temperature
    params = sampling_lib.SamplingParams(temperature=1.0, top_k=1)
    for seed in range(5):
        tok = sampling_lib.sample(logits, jax.random.PRNGKey(seed), params)
        assert int(tok[0]) == 3
    # top_p tiny → only the argmax survives
    params = sampling_lib.SamplingParams(temperature=1.0, top_p=0.01)
    tok = sampling_lib.sample(logits, key, params)
    assert int(tok[0]) == 3
    # greedy path
    params = sampling_lib.SamplingParams(temperature=0.0)
    assert int(sampling_lib.sample(logits, None, params)[0]) == 3


def test_benchmark_reports_metrics(tiny_engine):
    orch = orch_lib.Orchestrator(tiny_engine)
    metrics = orch.benchmark([[1, 2, 3]] * 3, max_new_tokens=4)
    assert metrics['request_throughput_rps'] > 0
    assert metrics['output_token_throughput_tps'] > 0
    assert metrics['mean_ttft_s'] >= 0


def test_per_slot_temperature_isolation(tiny_engine):
    """A greedy request batched with a sampled one stays deterministic."""
    greedy_prompt = [5, 17, 3]
    expected = _reference_greedy(tiny_engine.params, greedy_prompt, 6)
    orch = orch_lib.Orchestrator(tiny_engine, seed=123)
    greedy_req = orch.submit(orch_lib.Request(
        prompt_tokens=greedy_prompt, max_new_tokens=6, temperature=0.0))
    orch.submit(orch_lib.Request(
        prompt_tokens=[9, 8, 7], max_new_tokens=6, temperature=1.5))
    orch.run_until_drained()
    assert greedy_req.output_tokens == expected


def test_oversized_prompt_rejected_not_crashing(tiny_engine):
    orch = orch_lib.Orchestrator(tiny_engine)
    bad = orch.submit(orch_lib.Request(prompt_tokens=[1] * 1000,
                                       max_new_tokens=4))
    good = orch.submit(orch_lib.Request(prompt_tokens=[1, 2, 3],
                                        max_new_tokens=4))
    orch.run_until_drained()
    assert bad.done and bad.error is not None and bad.output_tokens == []
    assert good.done and good.error is None and len(good.output_tokens) == 4
    # All slots back in the pool.
    assert len(orch._free_slots) == tiny_engine.config.max_slots


def test_prompt_exceeding_kv_budget_rejected():
    """Prompt fits a prefill bucket but not max_target_len → rejected."""
    config = engine_lib.EngineConfig(
        model=llama.LLAMA_TINY, max_slots=2, max_target_len=16,
        prefill_buckets=(8, 32))
    params = llama.init(llama.LLAMA_TINY, jax.random.PRNGKey(0))
    engine = engine_lib.InferenceEngine(config, params)
    orch = orch_lib.Orchestrator(engine)
    bad = orch.submit(orch_lib.Request(prompt_tokens=[1] * 20,
                                       max_new_tokens=4))
    orch.run_until_drained()
    assert bad.done and bad.error is not None
    assert len(orch._free_slots) == config.max_slots


def test_default_decode_key_advances(tiny_engine):
    """decode_step without an explicit key must not reuse PRNG state."""
    k0 = tiny_engine._key
    state = tiny_engine.init_decode_state()
    state, _ = tiny_engine.decode_step(state)
    assert not bool(jnp.all(tiny_engine._key == k0))


def test_batched_topk_per_row():
    logits = jnp.asarray([[1.0, 2.0, 3.0, 4.0],
                          [4.0, 3.0, 2.0, 1.0]])
    temps = jnp.asarray([1.0, 0.0])
    top_k = jnp.asarray([1, 0])
    toks = sampling_lib.sample_batched(logits, jax.random.PRNGKey(0),
                                       temps, top_k=top_k)
    assert int(toks[0]) == 3  # top_k=1 → argmax despite temperature
    assert int(toks[1]) == 0  # greedy row


def test_moe_config_accepted_by_engine():
    """MoE now provides the prefill_hidden/decode_forward pair, so the
    engine binds it like any dense family (decode equality covered by
    test_moe_cached_decode_matches_full_forward)."""
    from skypilot_tpu.models import moe
    config = engine_lib.EngineConfig(model=moe.MOE_TINY)
    engine = engine_lib.InferenceEngine(config, params={})
    assert engine._model_lib is moe


def test_run_until_drained_marks_truncated(tiny_engine):
    orch = orch_lib.Orchestrator(tiny_engine)
    req = orch.submit(orch_lib.Request(prompt_tokens=[1, 2, 3],
                                       max_new_tokens=50))
    orch.run_until_drained(max_steps=2)
    assert req.done and req.error is not None
    assert len(orch._free_slots) == tiny_engine.config.max_slots


@pytest.mark.parametrize('variant', ['qwen-tiny', 'qwen3-tiny'])
def test_qwen_cached_decode_matches_full_forward(variant):
    """The engine's model binding is family-generic: Qwen (biased QKV
    and QK-norm variants) decodes through the slot KV cache exactly as
    its full re-forward greedy reference."""
    from skypilot_tpu.models import qwen
    c = qwen.CONFIGS[variant]
    params = qwen.init(c, jax.random.PRNGKey(0))
    config = engine_lib.EngineConfig(
        model=c, max_slots=2, max_target_len=32, prefill_buckets=(16,))
    engine = engine_lib.InferenceEngine(config, params)

    prompt = [5, 17, 3, 99, 42]
    n_new = 6
    tokens = list(prompt)
    for _ in range(n_new):
        logits = qwen.forward(c, params, jnp.asarray([tokens], jnp.int32))
        tokens.append(int(jnp.argmax(logits[0, -1])))
    expected = tokens[len(prompt):]

    orch = orch_lib.Orchestrator(engine)
    outputs = orch.generate([prompt], max_new_tokens=n_new)
    assert outputs[0] == expected


def test_gemma_cached_decode_matches_full_forward():
    """Gemma serving: tied soft-capped head through the engine's
    model-owned lm_logits hook; slot-cache decode equals full
    re-forward greedy."""
    from skypilot_tpu.models import gemma
    c = gemma.GEMMA_TINY
    params = gemma.init(c, jax.random.PRNGKey(0))
    config = engine_lib.EngineConfig(
        model=c, max_slots=2, max_target_len=32, prefill_buckets=(16,))
    engine = engine_lib.InferenceEngine(config, params)

    prompt = [5, 17, 3, 99, 42]
    n_new = 6
    tokens = list(prompt)
    for _ in range(n_new):
        logits = gemma.forward(c, params,
                               jnp.asarray([tokens], jnp.int32))
        tokens.append(int(jnp.argmax(logits[0, -1])))
    expected = tokens[len(prompt):]

    orch = orch_lib.Orchestrator(engine)
    outputs = orch.generate([prompt], max_new_tokens=n_new)
    assert outputs[0] == expected


def test_moe_cached_decode_matches_full_forward():
    """MoE serving: slot-cache decode equals full re-forward greedy.

    Decode routing uses capacity == slot count (never drops), so
    equality with the full forward holds exactly in the no-drop regime
    — pinned here via a capacity_factor that admits every assignment.
    (With a tight capacity_factor, training-time capacity dropping makes
    the full forward diverge from incremental decode by design.)"""
    import dataclasses as dc
    from skypilot_tpu.models import moe
    c = dc.replace(moe.MOE_TINY, capacity_factor=float(moe.MOE_TINY.n_experts))
    params = moe.init(c, jax.random.PRNGKey(0))
    config = engine_lib.EngineConfig(
        model=c, max_slots=2, max_target_len=32, prefill_buckets=(16,))
    engine = engine_lib.InferenceEngine(config, params)

    prompt = [5, 17, 3, 99, 42]
    n_new = 6
    tokens = list(prompt)
    for _ in range(n_new):
        logits = moe.forward(c, params, jnp.asarray([tokens], jnp.int32))
        tokens.append(int(jnp.argmax(logits[0, -1])))
    expected = tokens[len(prompt):]

    orch = orch_lib.Orchestrator(engine)
    outputs = orch.generate([prompt], max_new_tokens=n_new)
    assert outputs[0] == expected


def test_engine_rejects_family_missing_serving_hooks(monkeypatch):
    """The missing-hook guard still has teeth now that every in-tree
    family serves: a family without the trio is rejected up front."""
    import types

    from skypilot_tpu import models
    stub = types.ModuleType('stub_family')   # no serving hooks at all
    monkeypatch.setattr(models, 'module_for', lambda cfg: stub)
    config = engine_lib.EngineConfig(model=llama.LLAMA_TINY)
    with pytest.raises(NotImplementedError, match='prefill_hidden'):
        engine_lib.InferenceEngine(config, params={})


class TestInt8KvCache:
    """kv_dtype=int8: half-HBM cache with per-(position, head) scales,
    quantized in slot_cache_attend — shared by every family."""

    def _engines(self, model_cfg, init_fn):
        params = init_fn(model_cfg, jax.random.PRNGKey(0))
        mk = lambda dtype: engine_lib.InferenceEngine(
            engine_lib.EngineConfig(model=model_cfg, max_slots=2,
                                    max_target_len=32,
                                    prefill_buckets=(16,),
                                    kv_dtype=dtype), params)
        return mk(jnp.bfloat16), mk(jnp.int8)

    def test_llama_int8_matches_bf16_greedy(self):
        bf16, int8 = self._engines(llama.LLAMA_TINY, llama.init)
        prompt = [5, 17, 3, 99, 42]
        out_ref = orch_lib.Orchestrator(bf16).generate(
            [prompt], max_new_tokens=6)
        out_q = orch_lib.Orchestrator(int8).generate(
            [prompt], max_new_tokens=6)
        # 7-bit mantissa quantization error is far below the tiny
        # model's logit gaps: greedy decode is unchanged.
        assert out_q == out_ref

    def test_qwen_int8_decodes(self):
        from skypilot_tpu.models import qwen
        bf16, int8 = self._engines(qwen.QWEN3_TINY, qwen.init)
        prompt = [1, 2, 3]
        out_ref = orch_lib.Orchestrator(bf16).generate(
            [prompt], max_new_tokens=4)
        out_q = orch_lib.Orchestrator(int8).generate(
            [prompt], max_new_tokens=4)
        # Tiny qk-norm logit gaps sit near the quantization noise floor,
        # so exact greedy equality is not guaranteed here (it is for the
        # llama tiny above); the quantized path must still produce the
        # same first step and a full, valid generation.
        assert out_q[0][0] == out_ref[0][0]
        assert len(out_q[0]) == 4
        assert all(0 <= t < qwen.QWEN3_TINY.vocab_size for t in out_q[0])

    def test_cache_is_actually_int8(self):
        _, int8 = self._engines(llama.LLAMA_TINY, llama.init)
        state = int8.init_decode_state()
        data, scale = state['kv_k']
        assert data.dtype == jnp.int8
        assert scale.dtype == jnp.float32
        assert scale.shape == data.shape[:-1] + (1,)
        # int8 + fp32/hd scale ≈ 0.53× the bf16 cache bytes.
        bf16_bytes = data.size * 2
        q_bytes = data.size + scale.size * 4
        # Tiny head_dim=16 pays 4B/16 values of scale overhead (0.625x);
        # real models (hd=128) sit at ~0.52x.
        assert q_bytes < 0.65 * bf16_bytes

    def test_quantize_roundtrip_error_bounded(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 2, 16),
                              jnp.float32)
        q, s = llama.quantize_kv(x)
        back = llama.dequantize_kv(q, s, jnp.float32)
        err = float(jnp.max(jnp.abs(back - x)))
        amax = float(jnp.max(jnp.abs(x)))
        assert err <= amax / 127.0 + 1e-6


def test_mistral_sliding_window_cached_decode():
    """Sliding-window decode through the slot cache equals full
    re-forward greedy (the window mask applies in both paths), and the
    window demonstrably constrains attention."""
    from skypilot_tpu.models import llama as llama_lib
    c = llama_lib.MISTRAL_TINY
    params = llama_lib.init(c, jax.random.PRNGKey(0))
    config = engine_lib.EngineConfig(
        model=c, max_slots=2, max_target_len=32, prefill_buckets=(16,))
    engine = engine_lib.InferenceEngine(config, params)

    prompt = [5, 17, 3, 99, 42, 7, 8, 9, 10, 11, 12, 13]
    n_new = 6
    tokens = list(prompt)
    for _ in range(n_new):
        logits = llama_lib.forward(c, params,
                                   jnp.asarray([tokens], jnp.int32))
        tokens.append(int(jnp.argmax(logits[0, -1])))
    expected = tokens[len(prompt):]

    orch = orch_lib.Orchestrator(engine)
    outputs = orch.generate([prompt], max_new_tokens=n_new)
    assert outputs[0] == expected

    # Same weights WITHOUT the window decode differently (window=8 is
    # tighter than the 12-token prompt).
    import dataclasses as dc
    c_full = dc.replace(c, sliding_window=None)
    logits_full = llama_lib.forward(c_full, params,
                                    jnp.asarray([prompt], jnp.int32))
    logits_win = llama_lib.forward(c, params,
                                   jnp.asarray([prompt], jnp.int32))
    assert float(jnp.abs(logits_full - logits_win).max()) > 1e-4


# ---- chunked prefill / prefix cache / multi-step decode ----


def _engine(model_cfg=None, **overrides):
    model_cfg = model_cfg or llama.LLAMA_TINY
    params = llama.init(model_cfg, jax.random.PRNGKey(0))
    kwargs = dict(model=model_cfg, max_slots=4, max_target_len=64,
                  prefill_buckets=(8, 16))
    kwargs.update(overrides)
    return engine_lib.InferenceEngine(
        engine_lib.EngineConfig(**kwargs), params)


def test_chunked_prefill_matches_full_forward():
    """A prompt beyond the largest bucket prefills in chunks through
    verify_forward; greedy decode afterwards must equal full-forward
    greedy exactly (prefix rows identical, chunk masking correct)."""
    engine = _engine()
    assert engine.max_admit_len == 63
    prompt = [(i * 13 + 5) % 256 for i in range(40)]   # 40 > bucket 16
    n_new = 6
    expected = _reference_greedy(engine.params, prompt, n_new)
    outputs = orch_lib.Orchestrator(engine).generate(
        [prompt], max_new_tokens=n_new)
    assert outputs[0] == expected


def test_chunked_prefill_multiple_exact_chunks():
    """Prompt length an exact multiple of the chunk size (no padded
    tail — the last-chunk logits row is the chunk's final row)."""
    engine = _engine()
    prompt = [(i * 7 + 1) % 256 for i in range(32)]    # 2 × bucket 16
    expected = _reference_greedy(engine.params, prompt, 4)
    outputs = orch_lib.Orchestrator(engine).generate(
        [prompt], max_new_tokens=4)
    assert outputs[0] == expected


def test_prefix_cache_reuse_outputs_unchanged():
    """Two prompts sharing a >=MIN_REUSE-token prefix: the second
    reuses cached KV rows and must decode identically to cold."""
    shared = [(i * 11 + 2) % 256 for i in range(18)]
    p1 = shared + [7, 8]
    p2 = shared + [9, 10, 11, 12]
    cold = _engine()
    expected1 = orch_lib.Orchestrator(cold).generate(
        [p1], max_new_tokens=5)[0]
    expected2 = orch_lib.Orchestrator(cold).generate(
        [p2], max_new_tokens=5)[0]

    warm = _engine(prefix_cache_entries=4)
    orch = orch_lib.Orchestrator(warm)
    assert orch.generate([p1], max_new_tokens=5)[0] == expected1
    assert orch.generate([p2], max_new_tokens=5)[0] == expected2
    stats = warm.prefix_cache_stats
    assert stats['hits'] >= 1
    assert stats['tokens_reused'] >= 16


def test_prefix_cache_identical_prompt_hit():
    """The same prompt twice: the rerun reuses all but the last token's
    rows and still matches cold greedy output exactly."""
    prompt = [(i * 3 + 1) % 256 for i in range(24)]
    cold = _engine()
    expected = orch_lib.Orchestrator(cold).generate(
        [prompt], max_new_tokens=5)[0]
    warm = _engine(prefix_cache_entries=2)
    orch = orch_lib.Orchestrator(warm)
    assert orch.generate([prompt], max_new_tokens=5)[0] == expected
    assert orch.generate([prompt], max_new_tokens=5)[0] == expected
    assert warm.prefix_cache_stats['hits'] == 1


def test_prefix_cache_lru_eviction():
    warm = _engine(prefix_cache_entries=1)
    orch = orch_lib.Orchestrator(warm)
    p1 = [1] * 20
    p2 = [2] * 20
    orch.generate([p1], max_new_tokens=2)
    orch.generate([p2], max_new_tokens=2)   # evicts p1
    assert warm.prefix_cache_stats['entries'] == 1
    # p1 again: must miss (evicted), still decode correctly.
    cold = _engine()
    expected = orch_lib.Orchestrator(cold).generate(
        [p1], max_new_tokens=3)[0]
    assert orch.generate([p1], max_new_tokens=3)[0] == expected


def test_prefix_cache_rejected_for_custom_layout():
    from skypilot_tpu.models import deepseek
    params = deepseek.init(deepseek.DEEPSEEK_TINY, jax.random.PRNGKey(0))
    with pytest.raises(NotImplementedError):
        engine_lib.InferenceEngine(
            engine_lib.EngineConfig(model=deepseek.DEEPSEEK_TINY,
                                    max_slots=2, max_target_len=32,
                                    prefill_buckets=(16,),
                                    prefix_cache_entries=2), params)


def test_multi_step_decode_matches_single_step(tiny_engine):
    """decode_steps=4 fuses steps on-device; outputs must be identical
    to per-token decoding, including an EOS mid-batch and a budget that
    is not a multiple of the fused step count."""
    prompts = [[1, 2, 3], [7, 8, 9, 10, 11], [20, 21]]
    n_new = 6   # not a multiple of 4
    expected = [_reference_greedy(tiny_engine.params, p, n_new)
                for p in prompts]
    orch = orch_lib.Orchestrator(tiny_engine, decode_steps=4)
    assert orch.generate(prompts, max_new_tokens=n_new) == expected
    # decode_steps DEEPER than the whole budget (the bench's ds=16
    # rungs with short max_new): must truncate exactly, not run over.
    orch16 = orch_lib.Orchestrator(tiny_engine, decode_steps=16)
    assert orch16.generate(prompts, max_new_tokens=n_new) == expected
    # EOS mid-fused-batch: stop exactly at the EOS position.
    full = _reference_greedy(tiny_engine.params, [5, 17, 3], 10)
    eos = full[4]
    orch2 = orch_lib.Orchestrator(tiny_engine, decode_steps=4)
    out = orch2.generate([[5, 17, 3]], max_new_tokens=10,
                         eos_token_id=eos)
    assert out[0] == full[:4]
    assert len(orch2._free_slots) == tiny_engine.config.max_slots


def test_multi_step_decode_near_kv_budget():
    """Fused steps past a slot's KV budget: the extra scan steps write
    at clamped positions after the last kept token — they must not
    change any kept output vs per-token decoding. (Compared against the
    single-step path, not the full-forward reference: at this tiny
    max_target_len the kernel-vs-XLA bf16 rounding difference flips a
    near-tied argmax in the random-weight model, which is a numerics
    artifact, not a cache-corruption signal.)"""
    prompt = [3, 1, 4, 1, 5]
    single = orch_lib.Orchestrator(
        _engine(max_target_len=16, prefill_buckets=(8,))).generate(
            [prompt], max_new_tokens=50)                # clamped to 11
    fused = orch_lib.Orchestrator(
        _engine(max_target_len=16, prefill_buckets=(8,)),
        decode_steps=4).generate([prompt], max_new_tokens=50)
    assert fused == single
    assert len(single[0]) == 11


def test_fused_decode_lengths_capped_at_kv_budget():
    """Slot lengths must never exceed max_target_len even when fused
    steps run past a finished request (the decode kernels' block
    index_maps would otherwise read out-of-range blocks on TPU)."""
    engine = _engine(max_target_len=16, prefill_buckets=(8,))
    orch = orch_lib.Orchestrator(engine, decode_steps=4)
    orch.generate([[3, 1, 4, 1, 5]], max_new_tokens=50)
    lengths = np.asarray(jax.device_get(orch.state['lengths']))
    assert (lengths <= engine.config.max_target_len).all()


def test_speculative_long_prompt_chunk_prefills_draft(monkeypatch):
    """A prompt beyond the largest bucket must chunk-prefill BOTH the
    target and the draft (a bucketed draft prefill would raise with the
    slot already claimed), and still equal plain greedy decoding.
    Both runs are pinned to the XLA attend: speculation decodes through
    verify_forward's masked path while plain decode uses the Pallas
    kernel, and their bf16 rounding difference flips a near-tied argmax
    on the random-weight tiny model (numerics, not a logic bug)."""
    monkeypatch.setenv('XSKY_DECODE_ATTN', 'xla')
    model = llama.LLAMA_TINY
    params = llama.init(model, jax.random.PRNGKey(0))
    mk = lambda: engine_lib.InferenceEngine(
        engine_lib.EngineConfig(model=model, max_slots=2,
                                max_target_len=64,
                                prefill_buckets=(8, 16)), params)
    prompt = [(i * 5 + 3) % 256 for i in range(40)]    # 40 > bucket 16
    expected = orch_lib.Orchestrator(mk()).generate(
        [prompt], max_new_tokens=6)
    spec = orch_lib.SpeculativeOrchestrator(mk(), mk(), gamma=3)
    assert spec.generate([prompt], max_new_tokens=6) == expected


class TestRepetitionPenalties:

    def test_frequency_penalty_suppresses_repeats(self):
        """A strong frequency penalty must change greedy output away
        from the unpenalized continuation once tokens repeat — and the
        unpenalized request in the same batch must be unaffected."""
        engine = _engine()
        orch = orch_lib.Orchestrator(engine)
        plain = orch.submit(orch_lib.Request(
            prompt_tokens=[5, 17, 3], max_new_tokens=10))
        penalized = orch.submit(orch_lib.Request(
            prompt_tokens=[5, 17, 3], max_new_tokens=10,
            frequency_penalty=2.0))
        orch.run_until_drained()
        expected = _reference_greedy(engine.params, [5, 17, 3], 10)
        assert plain.output_tokens == expected
        # The tiny random model repeats heavily; the penalty must
        # break at least one repeat.
        assert penalized.output_tokens != expected
        # And no token appears as often as in the unpenalized run's
        # dominant repeat.
        from collections import Counter
        top_plain = Counter(plain.output_tokens).most_common(1)[0][1]
        top_pen = Counter(penalized.output_tokens).most_common(1)[0][1]
        assert top_pen <= top_plain

    def test_penalties_match_manual_reference(self):
        """Greedy + frequency/presence penalties equals a manual
        full-forward loop applying the same logit adjustment."""
        engine = _engine()
        prompt = [7, 8, 9]
        pres, freq = 0.7, 0.4
        tokens = list(prompt)
        counts = {}
        expected = []
        first = True
        for _ in range(8):
            logits = np.array(llama.forward(
                llama.LLAMA_TINY, engine.params,
                jnp.asarray([tokens], jnp.int32))[0, -1], np.float32,
                copy=True)
            if not first:
                for t, c in counts.items():
                    logits[t] -= pres * (c > 0) + freq * c
            tok = int(np.argmax(logits))
            expected.append(tok)
            counts[tok] = counts.get(tok, 0) + 1
            tokens.append(tok)
            first = False
        orch = orch_lib.Orchestrator(engine)
        request = orch.submit(orch_lib.Request(
            prompt_tokens=prompt, max_new_tokens=8,
            presence_penalty=pres, frequency_penalty=freq))
        orch.run_until_drained()
        assert request.output_tokens == expected

    def test_fused_steps_match_single_with_penalties(self):
        prompt = [3, 1, 4]
        mk = lambda: _engine()
        o1, o4 = orch_lib.Orchestrator(mk()), \
            orch_lib.Orchestrator(mk(), decode_steps=4)
        r1 = o1.submit(orch_lib.Request(prompt_tokens=prompt,
                                        max_new_tokens=9,
                                        frequency_penalty=1.5))
        o1.run_until_drained()
        r4 = o4.submit(orch_lib.Request(prompt_tokens=prompt,
                                        max_new_tokens=9,
                                        frequency_penalty=1.5))
        o4.run_until_drained()
        assert r1.output_tokens == r4.output_tokens

    def test_slot_reuse_resets_counts(self):
        """A penalized request in a reused slot must not inherit the
        previous occupant's counts."""
        engine = _engine(max_slots=1)
        orch = orch_lib.Orchestrator(engine)
        first = orch.submit(orch_lib.Request(
            prompt_tokens=[5, 17, 3], max_new_tokens=6,
            frequency_penalty=2.0))
        orch.run_until_drained()
        second = orch.submit(orch_lib.Request(
            prompt_tokens=[5, 17, 3], max_new_tokens=6,
            frequency_penalty=2.0))
        orch.run_until_drained()
        assert first.output_tokens == second.output_tokens


class TestInterleavedChunkedPrefill:

    def test_outputs_equal_non_interleaved(self):
        """Interleaving changes scheduling, never outputs."""
        prompts = [[(i * 13 + 5) % 256 for i in range(40)],   # 3 chunks
                   [1, 2, 3],
                   [(i * 7 + 2) % 256 for i in range(50)]]    # 4 chunks
        mk = lambda: _engine()
        o_on = orch_lib.Orchestrator(mk())
        assert o_on.interleave_prefill
        out_on = o_on.generate(prompts, max_new_tokens=5)
        o_off = orch_lib.Orchestrator(mk())
        o_off.interleave_prefill = False
        out_off = o_off.generate(prompts, max_new_tokens=5)
        assert out_on == out_off

    def test_short_request_decodes_during_long_prefill(self):
        """A long prompt's chunked prefill must not stall an active
        stream: the short request keeps emitting while the long one is
        mid-prefill."""
        engine = _engine()
        orch = orch_lib.Orchestrator(engine)
        long_req = orch.submit(orch_lib.Request(
            prompt_tokens=[(i * 11 + 1) % 256 for i in range(60)],
            max_new_tokens=3))
        orch.step()                       # claim slot, chunk 1 of 4
        assert orch._partials and not long_req.output_tokens
        short = orch.submit(orch_lib.Request(prompt_tokens=[5, 6, 7],
                                             max_new_tokens=8))
        orch.step()                       # short admits AND decodes
        assert len(short.output_tokens) >= 2
        assert orch._partials             # long still mid-prefill
        orch.run_until_drained()
        assert long_req.done and len(long_req.output_tokens) == 3
        assert short.done and len(short.output_tokens) == 8

    def test_cancel_mid_prefill_frees_slot(self):
        engine = _engine(max_slots=1)
        orch = orch_lib.Orchestrator(engine)
        long_req = orch.submit(orch_lib.Request(
            prompt_tokens=[(i * 3 + 1) % 256 for i in range(60)],
            max_new_tokens=3))
        orch.step()
        assert orch._partials
        long_req.cancel_requested = True
        follow = orch.submit(orch_lib.Request(prompt_tokens=[9, 9, 9],
                                              max_new_tokens=2))
        orch.run_until_drained()
        assert long_req.done and long_req.output_tokens == []
        assert follow.done and len(follow.output_tokens) == 2
        assert len(orch._free_slots) == 1

    def test_speculative_interleaved_long_prompt(self):
        """Speculation + interleaved chunked prefill: the draft mirror
        runs at admission completion (the _finish_admit hook), so
        outputs still equal plain greedy decoding."""
        model = llama.LLAMA_TINY
        params = llama.init(model, jax.random.PRNGKey(0))
        mk = lambda: engine_lib.InferenceEngine(
            engine_lib.EngineConfig(model=model, max_slots=2,
                                    max_target_len=64,
                                    prefill_buckets=(8, 16)), params)
        import os
        os.environ['XSKY_DECODE_ATTN'] = 'xla'
        try:
            prompt = [(i * 5 + 3) % 256 for i in range(40)]
            expected = orch_lib.Orchestrator(mk()).generate(
                [prompt], max_new_tokens=6)
            spec = orch_lib.SpeculativeOrchestrator(mk(), mk(), gamma=3)
            assert spec.interleave_prefill
            assert spec.generate([prompt], max_new_tokens=6) == expected
        finally:
            os.environ.pop('XSKY_DECODE_ATTN', None)

    def test_prefill_budget_bounds_chunks_per_tick(self):
        """Two concurrent long prompts advance one chunk per tick
        total (budget 1): ticks-to-complete reflects the cap."""
        engine = _engine(max_slots=4)
        orch = orch_lib.Orchestrator(engine)
        for _ in range(2):
            orch.submit(orch_lib.Request(
                prompt_tokens=[(i * 11 + 1) % 256 for i in range(60)],
                max_new_tokens=2))
        orch.step()   # both claimed; 1 chunk ran (budget)
        assert len(orch._partials) == 2
        # 4 chunks each → 8 chunk-ticks total; after 6 more ticks at
        # budget 1, at least one must still be mid-prefill.
        for _ in range(6):
            orch.step()
        assert orch._partials
        orch.run_until_drained()
        assert not orch._partials


def test_penalties_on_sharded_mesh(monkeypatch):
    """Repetition penalties under a tensor-parallel mesh: the
    [slots, vocab] count ops must compile and stay per-slot correct."""
    monkeypatch.setenv('XSKY_DECODE_ATTN', 'xla')
    mesh = mesh_lib.build_mesh(mesh_lib.MeshPlan(data=4, tensor=2))
    config = engine_lib.EngineConfig(
        model=llama.LLAMA_TINY, max_slots=4, max_target_len=32,
        prefill_buckets=(16,))
    params = llama.init(llama.LLAMA_TINY, jax.random.PRNGKey(0))
    sharded = engine_lib.InferenceEngine(config, params, mesh=mesh)
    plain = engine_lib.InferenceEngine(config, params)

    def run(engine):
        orch = orch_lib.Orchestrator(engine)
        request = orch.submit(orch_lib.Request(
            prompt_tokens=[5, 17, 3], max_new_tokens=8,
            frequency_penalty=2.0))
        orch.run_until_drained()
        return request.output_tokens

    assert run(sharded) == run(plain)


def test_gemma2_int8_kv_decodes():
    """Gemma-2's pair scan over QUANTIZED (values, scale) cache tuples:
    int8 KV greedy equals bf16 greedy (the pair reshape must keep
    values and scales together)."""
    from skypilot_tpu.models import gemma
    params = gemma.init(gemma.GEMMA2_TINY, jax.random.PRNGKey(0))
    mk = lambda dtype: engine_lib.InferenceEngine(
        engine_lib.EngineConfig(model=gemma.GEMMA2_TINY, max_slots=2,
                                max_target_len=32,
                                prefill_buckets=(16,),
                                kv_dtype=dtype), params)
    prompt = [5, 17, 3, 99, 42]
    out_ref = orch_lib.Orchestrator(mk(jnp.bfloat16)).generate(
        [prompt], max_new_tokens=6)
    out_q = orch_lib.Orchestrator(mk(jnp.int8)).generate(
        [prompt], max_new_tokens=6)
    assert out_q == out_ref


class TestBatchedAdmission:
    """Wave admission: same-bucket prefills fuse into one forward + one
    scatter insert (2 dispatches per wave instead of 2 per request) —
    the TTFT lever for dispatch-bound links. Outputs must be EXACTLY
    the per-request path's."""

    def test_wave_batches_and_matches_reference(self, tiny_engine,
                                                monkeypatch):
        calls = []
        orig = tiny_engine.prefill_insert_batch

        def spy(state, args, slots):
            calls.append(len(args))
            return orig(state, args, slots)

        monkeypatch.setattr(tiny_engine, 'prefill_insert_batch', spy)
        prompts = [[1, 2, 3], [7, 8, 9], [20, 21], [5, 17, 3, 9]]
        n_new = 6
        expected = [_reference_greedy(tiny_engine.params, p, n_new)
                    for p in prompts]
        orch = orch_lib.Orchestrator(tiny_engine)
        assert orch.generate(prompts, max_new_tokens=n_new) == expected
        # All four fit one bucket and 4 slots: one batched wave.
        assert calls == [4]

    def test_wave_mixed_buckets_and_sampling(self, tiny_engine,
                                             monkeypatch):
        """Rows with different buckets group separately."""
        calls = []
        orig = tiny_engine.prefill_insert_batch

        def spy(state, args, slots):
            calls.append(sorted(len(p) for p, _ in args))
            return orig(state, args, slots)

        monkeypatch.setattr(tiny_engine, 'prefill_insert_batch', spy)
        short = [[1, 2, 3], [4, 5, 6]]                  # bucket 16
        long = [list(range(1, 21)), list(range(3, 25))]  # bucket 32
        n_new = 4
        expected = [_reference_greedy(tiny_engine.params, p, n_new)
                    for p in short + long]
        orch = orch_lib.Orchestrator(tiny_engine)
        reqs = [orch.submit(orch_lib.Request(prompt_tokens=list(p),
                                             max_new_tokens=n_new))
                for p in short + long]
        orch.run_until_drained()
        assert [r.output_tokens for r in reqs] == expected
        assert sorted(map(tuple, calls)) == [(3, 3), (20, 22)]

    def test_wave_padding_to_pow2(self, tiny_engine, monkeypatch):
        """3 requests pad to 4 rows (next pow2) by repeating row 0 —
        outputs and slot state must be unaffected by the duplicate
        scatter row, and the forward must see the pow2-padded batch,
        not a full max_slots one."""
        rows = []
        orig = tiny_engine._prefill_batch

        def spy(params, tokens, *args, **kwargs):
            rows.append(tokens.shape[0])
            return orig(params, tokens, *args, **kwargs)

        monkeypatch.setattr(tiny_engine, '_prefill_batch', spy)
        prompts = [[1, 2, 3], [7, 8, 9, 10], [20, 21]]
        n_new = 5
        expected = [_reference_greedy(tiny_engine.params, p, n_new)
                    for p in prompts]
        orch = orch_lib.Orchestrator(tiny_engine)
        assert orch.generate(prompts, max_new_tokens=n_new) == expected
        assert rows == [4]
        assert sorted(orch._free_slots) == list(
            range(tiny_engine.config.max_slots))

    def test_small_wave_pads_to_pow2_not_max_slots(self, monkeypatch):
        """A 2-request wave on a wide engine pays a 2-row forward, not
        a max_slots-row one (advisor r4: full-slot padding was ~16x
        the needed prefill FLOPs)."""
        config = engine_lib.EngineConfig(
            model=llama.LLAMA_TINY, max_slots=8, max_target_len=64,
            prefill_buckets=(16,))
        params = llama.init(llama.LLAMA_TINY, jax.random.PRNGKey(0))
        engine = engine_lib.InferenceEngine(config, params)
        rows = []
        orig = engine._prefill_batch

        def spy(p, tokens, *args, **kwargs):
            rows.append(tokens.shape[0])
            return orig(p, tokens, *args, **kwargs)

        monkeypatch.setattr(engine, '_prefill_batch', spy)
        n_new = 3
        prompts = [[1, 2, 3], [7, 8, 9]]
        expected = [_reference_greedy(params, p, n_new)
                    for p in prompts]
        orch = orch_lib.Orchestrator(engine)
        assert orch.generate(prompts, max_new_tokens=n_new) == expected
        assert rows == [2]

    def test_batched_admission_knob_forces_single_path(self,
                                                       monkeypatch):
        """batched_admission=False routes every admission through the
        per-prompt path (compute-bound deployments opt out of wave
        fusion)."""
        config = engine_lib.EngineConfig(
            model=llama.LLAMA_TINY, max_slots=4, max_target_len=64,
            prefill_buckets=(16,), batched_admission=False)
        params = llama.init(llama.LLAMA_TINY, jax.random.PRNGKey(0))
        engine = engine_lib.InferenceEngine(config, params)
        calls = []
        orig = engine.prefill_insert_batch
        monkeypatch.setattr(
            engine, 'prefill_insert_batch',
            lambda s, a, sl: (calls.append(len(a)), orig(s, a, sl))[1])
        n_new = 3
        prompts = [[1, 2, 3], [7, 8, 9], [2, 4]]
        expected = [_reference_greedy(params, p, n_new)
                    for p in prompts]
        orch = orch_lib.Orchestrator(engine)
        assert orch.generate(prompts, max_new_tokens=n_new) == expected
        assert calls == []

    def test_logprobs_requests_use_single_path(self, tiny_engine,
                                               monkeypatch):
        calls = []
        orig = tiny_engine.prefill_insert_batch
        monkeypatch.setattr(
            tiny_engine, 'prefill_insert_batch',
            lambda s, a, sl: (calls.append(len(a)),
                              orig(s, a, sl))[1])
        orch = orch_lib.Orchestrator(tiny_engine)
        req = orch.submit(orch_lib.Request(prompt_tokens=[1, 2, 3],
                                           max_new_tokens=3,
                                           logprobs=2))
        orch.run_until_drained()
        assert calls == []          # single path (logprobs rows)
        assert len(req.token_logprobs) == len(req.output_tokens)

    def test_mixed_sampled_greedy_wave(self, tiny_engine):
        """A sampled request in slot/row 0 of a wave must not perturb
        the greedy rows — including via the pad rows, which repeat row
        0's inputs and draw their own samples (their scatter updates
        are dropped via the out-of-range sentinel slot)."""
        greedy = [[7, 8, 9], [20, 21, 22]]
        n_new = 5
        expected = [_reference_greedy(tiny_engine.params, p, n_new)
                    for p in greedy]
        orch = orch_lib.Orchestrator(tiny_engine, seed=7)
        sampled_req = orch.submit(orch_lib.Request(
            prompt_tokens=[1, 2, 3], max_new_tokens=n_new,
            temperature=1.3, top_k=4, top_p=0.9))
        greedy_reqs = [orch.submit(orch_lib.Request(
            prompt_tokens=list(p), max_new_tokens=n_new))
            for p in greedy]
        orch.run_until_drained()
        assert [r.output_tokens for r in greedy_reqs] == expected
        assert len(sampled_req.output_tokens) == n_new
        assert sorted(orch._free_slots) == list(
            range(tiny_engine.config.max_slots))

    def test_int8_kv_batched_insert(self):
        """Batched scatter into the QUANTIZED cache representation."""
        import jax.numpy as jnp
        config = engine_lib.EngineConfig(
            model=llama.LLAMA_TINY, max_slots=4, max_target_len=64,
            prefill_buckets=(16,), kv_dtype=jnp.int8)
        params = llama.init(llama.LLAMA_TINY, jax.random.PRNGKey(0))
        engine = engine_lib.InferenceEngine(config, params)
        prompts = [[1, 2, 3], [7, 8, 9, 10], [20, 21]]
        n_new = 5
        plain = orch_lib.Orchestrator(engine)
        out = plain.generate(prompts, max_new_tokens=n_new)
        # int8 KV is lossy vs the no-cache reference; parity bar is the
        # single-request path on the same engine.
        engine2 = engine_lib.InferenceEngine(config, params)
        single = orch_lib.Orchestrator(engine2)
        single._batched_admit = False
        assert out == single.generate(prompts, max_new_tokens=n_new)


# ---- paged KV cache + fused masked decode (the serving fast path) ----


def _paged_engine(**over):
    kw = dict(model=llama.LLAMA_TINY, max_slots=4, max_target_len=64,
              prefill_buckets=(16, 32), kv_page_size=8)
    kw.update(over)
    params = llama.init(llama.LLAMA_TINY, jax.random.PRNGKey(0))
    return engine_lib.InferenceEngine(engine_lib.EngineConfig(**kw),
                                      params)


class TestPagedKvParity:
    """The paged engine must be bit-identical to the dense slot cache:
    same model, same params, same sampling keys — only the KV layout
    (shared page arena + block tables) differs."""

    # Prompt lengths straddle the page_size=8 boundary (7/8/9) and
    # max_new pushes totals across 2-3 pages, so block-table lookups
    # cross physical page boundaries mid-decode.
    PROMPTS = [[5, 17, 3, 99, 42, 6, 7], [1, 2, 3, 4, 5, 6, 7, 8],
               [7, 8, 9, 10, 11, 12, 13, 14, 15]]

    def test_greedy_matches_dense(self, tiny_engine):
        n_new = 14
        dense = orch_lib.Orchestrator(tiny_engine, decode_steps=4)
        expected = dense.generate(self.PROMPTS, max_new_tokens=n_new)
        paged = orch_lib.Orchestrator(_paged_engine(), decode_steps=4)
        assert paged.generate(self.PROMPTS,
                              max_new_tokens=n_new) == expected

    def test_sampled_matches_dense(self):
        def run(eng):
            orch = orch_lib.Orchestrator(eng, seed=3, decode_steps=4)
            reqs = [orch.submit(orch_lib.Request(
                prompt_tokens=list(p), max_new_tokens=12,
                temperature=1.1, top_k=8, top_p=0.9))
                for p in self.PROMPTS]
            orch.run_until_drained()
            return [r.output_tokens for r in reqs]

        config = engine_lib.EngineConfig(
            model=llama.LLAMA_TINY, max_slots=4, max_target_len=64,
            prefill_buckets=(16, 32))
        params = llama.init(llama.LLAMA_TINY, jax.random.PRNGKey(0))
        dense_out = run(engine_lib.InferenceEngine(config, params))
        assert run(_paged_engine()) == dense_out
        assert all(len(o) == 12 for o in dense_out)

    def test_logprobs_match_dense(self, tiny_engine):
        def run(eng):
            orch = orch_lib.Orchestrator(eng, decode_steps=4)
            reqs = [orch.submit(orch_lib.Request(
                prompt_tokens=list(p), max_new_tokens=9, logprobs=3))
                for p in self.PROMPTS[:2]]
            orch.run_until_drained()
            return reqs

        for a, b in zip(run(tiny_engine), run(_paged_engine())):
            assert a.output_tokens == b.output_tokens
            assert np.allclose(a.token_logprobs, b.token_logprobs,
                               atol=1e-5)
            assert [sorted(d) for d in a.top_logprobs] == \
                   [sorted(d) for d in b.top_logprobs]

    def test_legacy_tick_on_paged_engine(self, tiny_engine,
                                         monkeypatch):
        """XSKY_DECODE_FAST_TICK=0 (the bench's baseline arm) must
        produce the same tokens on the paged engine: released slots'
        garbage fused rows land on sentinel pages, not live ones."""
        n_new = 10
        expected = orch_lib.Orchestrator(
            tiny_engine, decode_steps=4).generate(
                self.PROMPTS, max_new_tokens=n_new)
        monkeypatch.setenv('XSKY_DECODE_FAST_TICK', '0')
        legacy = orch_lib.Orchestrator(_paged_engine(), decode_steps=4)
        assert legacy.generate(self.PROMPTS,
                               max_new_tokens=n_new) == expected
        assert legacy.wasted_decode_steps > 0

    def test_slot_churn_reuses_pages(self, tiny_engine):
        """More requests than slots with mixed budgets: released pages
        get re-issued to later admissions and every stream still
        matches the dense engine."""
        prompts = self.PROMPTS * 3
        n_new = 11
        expected = orch_lib.Orchestrator(
            tiny_engine, decode_steps=4).generate(
                prompts, max_new_tokens=n_new)
        eng = _paged_engine()
        orch = orch_lib.Orchestrator(eng, decode_steps=4)
        assert orch.generate(prompts, max_new_tokens=n_new) == expected
        stats = eng.kv_page_stats
        assert stats['free'] == stats['total']


class TestPagedAdmission:

    def test_headroom_deferral_then_completion(self, tiny_engine):
        """An arena too small for all requests at once defers the
        overflow (no admission failure) and drains once streams
        finish; outputs still match the dense engine."""
        prompts = TestPagedKvParity.PROMPTS * 2
        n_new = 12
        expected = orch_lib.Orchestrator(
            tiny_engine, decode_steps=4).generate(
                prompts, max_new_tokens=n_new)
        # 6 pages of 8 = 48 tokens: fits ~2 concurrent budgets, not 6.
        eng = _paged_engine(kv_num_pages=6)
        orch = orch_lib.Orchestrator(eng, decode_steps=4)
        out = orch.generate(prompts, max_new_tokens=n_new)
        assert out == expected
        assert not orch._deferred
        stats = eng.kv_page_stats
        assert stats['free'] == stats['total'] == 6

    def test_never_fitting_budget_rejected(self):
        eng = _paged_engine(kv_num_pages=4)
        orch = orch_lib.Orchestrator(eng)
        req = orch.submit(orch_lib.Request(
            prompt_tokens=[1] * 10, max_new_tokens=50))
        orch.run_until_drained(max_steps=20)
        assert req.done and req.error is not None
        assert 'KV budget' in req.error

    def test_paged_config_validation(self):
        params = llama.init(llama.LLAMA_TINY, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match='must divide'):
            engine_lib.InferenceEngine(engine_lib.EngineConfig(
                model=llama.LLAMA_TINY, max_slots=2, max_target_len=64,
                prefill_buckets=(12,), kv_page_size=8), params)
        with pytest.raises(NotImplementedError, match='int8'):
            engine_lib.InferenceEngine(engine_lib.EngineConfig(
                model=llama.LLAMA_TINY, max_slots=2, max_target_len=64,
                prefill_buckets=(16,), kv_page_size=8,
                kv_dtype=jnp.int8), params)

    def test_paged_engine_blocks_speculation(self):
        assert not _paged_engine().supports_verify


class TestDeviceFinishMasking:
    """decode_steps_masked: finished slots stop sampling AND stop
    writing KV in-loop, on device."""

    def _insert_one(self, eng, prompt, max_new):
        state = eng.init_decode_state()
        assert eng.reserve_kv(0, len(prompt), max_new)
        first, kv, true_len = eng.prefill_any(prompt)
        return eng.insert(state, kv, first, true_len, 0), int(first)

    def _masked(self, eng, state, n, eos_id, remaining):
        slots = eng.config.max_slots
        eos = np.full((slots,), -1, np.int32)
        eos[0] = eos_id
        rem = np.full((slots,), 0, np.int32)
        rem[0] = remaining
        keys = jax.random.split(jax.random.PRNGKey(0), n)
        return eng.decode_steps_masked(
            state, n, jnp.zeros((slots,), jnp.float32), None, None,
            jnp.asarray(eos), jnp.asarray(rem), keys)

    def test_eos_row_invalidated_and_not_emitted(self):
        prompt = [5, 17, 3, 99, 42]
        eng = _paged_engine()
        state, _ = self._insert_one(eng, prompt, 32)
        state, _, toks, valid, _ = self._masked(eng, state, 6, -1, 32)
        stream = np.asarray(toks)[:, 0].tolist()
        eng2 = _paged_engine()
        state2, _ = self._insert_one(eng2, prompt, 32)
        state2, _, toks2, valid2, _ = self._masked(
            eng2, state2, 6, stream[2], 32)
        valid2 = np.asarray(valid2)[:, 0]
        assert np.asarray(toks2)[:, 0].tolist()[:3] == stream[:3]
        # Rows 0-1 kept; row 2 IS the EOS token → invalid (EOS never
        # emitted); rows 3+ masked out on device.
        assert valid2.tolist() == [True, True, False, False, False,
                                   False]
        assert not np.asarray(state2['active'])[0]

    def test_budget_exhaust_keeps_final_token(self):
        eng = _paged_engine()
        state, _ = self._insert_one(eng, [5, 17, 3], 3)
        state, rem, _, valid, _ = self._masked(eng, state, 6, -1, 3)
        valid = np.asarray(valid)[:, 0]
        # remaining=3: rows 0-2 valid (the exhausting token IS kept),
        # rows 3+ masked.
        assert valid.tolist() == [True, True, True, False, False,
                                  False]
        assert int(np.asarray(rem)[0]) == 0

    def test_no_kv_writes_after_finish(self):
        """After a slot deactivates, further fused steps must leave
        the ENTIRE page arena untouched: the finished slot's write
        position parks on the sentinel page and idle slots' tables are
        all-sentinel, so every scatter drops."""
        eng = _paged_engine()
        state, _ = self._insert_one(eng, [5, 17, 3, 99, 42], 4)
        state, _, _, valid, _ = self._masked(eng, state, 6, -1, 4)
        assert not np.asarray(state['active'])[0]
        k_before = np.asarray(jax.device_get(state['kv_k']))
        v_before = np.asarray(jax.device_get(state['kv_v']))
        lengths_before = int(np.asarray(state['lengths'])[0])
        state, _, _, valid2, _ = self._masked(eng, state, 6, -1, 0)
        assert not np.asarray(valid2).any()
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(state['kv_k'])), k_before)
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(state['kv_v'])), v_before)
        assert int(np.asarray(state['lengths'])[0]) == lengths_before

    def test_fast_tick_zero_wasted_steps(self, tiny_engine):
        """Orchestrator-level: a request EOS-ing mid-fused-batch burns
        zero post-finish rows on the fast tick."""
        prompt = [5, 17, 3, 99, 42]
        base = orch_lib.Orchestrator(tiny_engine, decode_steps=4)
        full = base.generate([prompt], max_new_tokens=12)[0]
        # First mid-stream token with no earlier occurrence — an EOS
        # id recurring earlier would (correctly) stop the stream there.
        cut = next(i for i in range(4, len(full) - 1)
                   if full[i] not in full[:i])
        eos = full[cut]
        orch = orch_lib.Orchestrator(_paged_engine(), decode_steps=4)
        req = orch.submit(orch_lib.Request(
            prompt_tokens=prompt, max_new_tokens=12,
            eos_token_id=eos))
        orch.run_until_drained()
        assert req.output_tokens == full[:cut]
        assert eos not in req.output_tokens
        assert orch.wasted_decode_steps == 0
