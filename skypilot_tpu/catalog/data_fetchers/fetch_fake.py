"""Deterministic catalog for the in-memory 'fake' cloud used in tests.

Plays the role moto plays in the reference's failover tests
(tests/test_failover.py:34-60): a small, fully offline cloud with multiple
regions/zones so zone→region→SKU failover logic is exercisable without any
cloud credentials.
"""
from __future__ import annotations

from typing import List

from skypilot_tpu.catalog import common

_ZONES = [
    ('fake-central1', 'fake-central1-a'),
    ('fake-central1', 'fake-central1-b'),
    ('fake-west1', 'fake-west1-a'),
    ('fake-east1', 'fake-east1-a'),
]


def generate() -> List[common.CatalogEntry]:
    entries: List[common.CatalogEntry] = []
    for region, zone in _ZONES:
        entries.append(
            common.CatalogEntry('fake-cpu-4', '', 0, 4, 16, 0, 0.10, 0.03,
                                region, zone))
        entries.append(
            common.CatalogEntry('fake-cpu-16', '', 0, 16, 64, 0, 0.40, 0.12,
                                region, zone))
        entries.append(
            common.CatalogEntry('fake-gpu-8', 'FAKEGPU', 8, 96, 680, 320,
                                20.0, 6.0, region, zone))
        # TPU twins: single host and a 4-host pod slice.
        entries.append(
            common.CatalogEntry('', 'tpu-v5e-8', 1, 112, 192, 128, 9.6, 3.36,
                                region, zone))
        entries.append(
            common.CatalogEntry('', 'tpu-v5e-32', 1, 448, 768, 512, 38.4,
                                13.44, region, zone))
        entries.append(
            common.CatalogEntry('', 'tpu-v5p-64', 1, 208 * 8, 448 * 8,
                                95.0 * 32, 134.4, 47.04, region, zone))
    return entries


if __name__ == '__main__':
    print(f'Wrote {common.save_catalog("fake", generate())}')
