"""SSH keypair management + per-cloud public-key injection.

Twin of sky/authentication.py (587 LoC): one framework-owned keypair
(~/.xsky/ssh/xsky-key[.pub]) generated on first use; clouds consume the
public key through their deploy variables (GCP: instance metadata
`ssh-keys`; Kubernetes pods use kubectl exec, no key needed).

Pure-Python Ed25519 via the `cryptography` package when available;
otherwise shells out to ssh-keygen (present wherever ssh is).
"""
from __future__ import annotations

import os
import subprocess
from typing import Tuple

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

_KEY_DIR = '~/.xsky/ssh'
PRIVATE_KEY_PATH = f'{_KEY_DIR}/xsky-key'
PUBLIC_KEY_PATH = f'{_KEY_DIR}/xsky-key.pub'
DEFAULT_SSH_USER = 'xsky'


def _keygen_cryptography(path: str) -> None:
    """Ed25519 keypair in OpenSSH format via the cryptography package
    (preferred: works in images without the openssh client)."""
    from cryptography.hazmat.primitives import serialization
    from cryptography.hazmat.primitives.asymmetric import ed25519
    key = ed25519.Ed25519PrivateKey.generate()
    private_bytes = key.private_bytes(
        encoding=serialization.Encoding.PEM,
        format=serialization.PrivateFormat.OpenSSH,
        encryption_algorithm=serialization.NoEncryption())
    public_bytes = key.public_key().public_bytes(
        encoding=serialization.Encoding.OpenSSH,
        format=serialization.PublicFormat.OpenSSH)
    with open(path, 'wb') as f:
        f.write(private_bytes)
    with open(path + '.pub', 'wb') as f:
        f.write(public_bytes + b' xsky\n')


def get_or_generate_keys() -> Tuple[str, str]:
    """Return (private_key_path, public_key_path), generating if needed.

    Generation is atomic-ish: written under a temp name then renamed, so
    concurrent launches race benignly.
    """
    private = os.path.expanduser(PRIVATE_KEY_PATH)
    public = os.path.expanduser(PUBLIC_KEY_PATH)
    if os.path.exists(private) and os.path.exists(public):
        return private, public
    os.makedirs(os.path.dirname(private), mode=0o700, exist_ok=True)
    tmp = private + '.tmp'
    for p in (tmp, tmp + '.pub'):
        if os.path.exists(p):
            os.remove(p)
    try:
        _keygen_cryptography(tmp)
    except ImportError:
        proc = subprocess.run(
            ['ssh-keygen', '-t', 'ed25519', '-N', '', '-q', '-f', tmp,
             '-C', 'xsky'],
            capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f'ssh-keygen failed: {proc.stderr}') from None
    os.chmod(tmp, 0o600)
    # Rename pub first: a reader seeing the private key may assume the
    # pub exists.
    os.replace(tmp + '.pub', public)
    os.replace(tmp, private)
    logger.info(f'Generated SSH keypair at {private}')
    return private, public


def public_key_content() -> str:
    _, public = get_or_generate_keys()
    with open(public, encoding='utf-8') as f:
        return f.read().strip()


def gcp_ssh_keys_metadata(ssh_user: str = DEFAULT_SSH_USER) -> str:
    """Value for the GCP `ssh-keys` instance/TPU metadata entry."""
    return f'{ssh_user}:{public_key_content()}'


def authorized_keys_setup_command(ssh_user: str = DEFAULT_SSH_USER) -> str:
    """Shell to append our public key on a host we can already reach
    (SSH node pools / BYO machines)."""
    key = public_key_content()
    return ('mkdir -p ~/.ssh && chmod 700 ~/.ssh && '
            f'grep -qF "{key}" ~/.ssh/authorized_keys 2>/dev/null || '
            f'echo "{key}" >> ~/.ssh/authorized_keys && '
            'chmod 600 ~/.ssh/authorized_keys')
