"""LoRA fine-tuning: adapter init/merge semantics, frozen base,
trainer integration (replicated adapters over a sharded base), family
generality."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from skypilot_tpu.models import llama
from skypilot_tpu.models import qwen
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.train import lora as lora_lib
from skypilot_tpu.train import trainer as trainer_lib

pytestmark = pytest.mark.slow  # jit compiles


def test_merge_identity_at_init():
    """b = 0 at init ⇒ merged model == base model exactly."""
    c = llama.LLAMA_TINY
    base = llama.init(c, jax.random.PRNGKey(0))
    adapters = lora_lib.init_lora(base, rank=4, key=jax.random.PRNGKey(1))
    merged = lora_lib.merge(base, adapters, alpha=16.0, rank=4)
    tokens = jnp.zeros((1, 8), jnp.int32)
    np.testing.assert_allclose(
        np.asarray(llama.forward(c, base, tokens)),
        np.asarray(llama.forward(c, merged, tokens)))


def test_adapter_tree_targets_and_size():
    c = llama.LLAMA_TINY
    base = llama.init(c, jax.random.PRNGKey(0))
    adapters = lora_lib.init_lora(base, rank=4, key=jax.random.PRNGKey(1))
    assert set(adapters['layers']) == {'wq', 'wk', 'wv', 'wo'}
    assert set(adapters['layers']['wq']) == {'a', 'b'}
    # Stacked layout preserved: [L, in, r] / [L, r, out].
    wq = base['layers']['wq']
    assert adapters['layers']['wq']['a'].shape == (wq.shape[0],
                                                   wq.shape[1], 4)
    assert adapters['layers']['wq']['b'].shape == (wq.shape[0], 4,
                                                   wq.shape[2])
    # Parameter-efficiency: adapters are a small fraction of the base.
    n_base = sum(x.size for x in jax.tree.leaves(base))
    assert lora_lib.count_params(adapters) < 0.2 * n_base


def test_custom_targets_include_mlp():
    c = llama.LLAMA_TINY
    base = llama.init(c, jax.random.PRNGKey(0))
    adapters = lora_lib.init_lora(
        base, rank=2, key=jax.random.PRNGKey(1),
        targets=('wq', 'w_gate', 'w_up', 'w_down'))
    assert set(adapters['layers']) == {'wq', 'w_gate', 'w_up', 'w_down'}


def test_unknown_targets_raise():
    c = llama.LLAMA_TINY
    base = llama.init(c, jax.random.PRNGKey(0))
    with pytest.raises(ValueError):
        lora_lib.init_lora(base, rank=2, key=jax.random.PRNGKey(1),
                           targets=('nonexistent',))
    # Partial match must ALSO raise (a crippled adapter subset trained
    # silently is worse than an error).
    with pytest.raises(ValueError, match='nonexistent'):
        lora_lib.init_lora(base, rank=2, key=jax.random.PRNGKey(1),
                           targets=('wq', 'nonexistent'))


def test_deepseek_mla_targets():
    """MLA has no wq/wk/wv: the default targets raise with the
    available names, and the MLA-appropriate ones adapt."""
    from skypilot_tpu.models import deepseek
    c = deepseek.DEEPSEEK_TINY
    base = deepseek.init(c, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match='w_ukv'):
        lora_lib.init_lora(base, rank=2, key=jax.random.PRNGKey(1))
    adapters = lora_lib.init_lora(base, rank=2,
                                  key=jax.random.PRNGKey(1),
                                  targets=('w_uq', 'w_ukv', 'wo'))
    merged = lora_lib.merge(base, adapters, alpha=8.0, rank=2)
    tokens = jnp.zeros((1, 8), jnp.int32)
    np.testing.assert_allclose(
        np.asarray(deepseek.forward(c, base, tokens)),
        np.asarray(deepseek.forward(c, merged, tokens)))


def _lora_trainer(model, **kwargs):
    config = trainer_lib.TrainConfig(
        model=model, global_batch_size=8, seq_len=16,
        optimizer='adamw', warmup_steps=1, lora_rank=4,
        # Adapters train at a much higher lr than full fine-tuning
        # (b starts at 0; the usual LoRA practice).
        learning_rate=1e-2,
        mesh_plan=mesh_lib.MeshPlan(), **kwargs)
    return trainer_lib.Trainer(config)


def test_lora_training_decreases_loss_and_freezes_base():
    trainer = _lora_trainer(llama.LLAMA_TINY)
    state = trainer.init_state()
    base_before = jax.tree.map(np.asarray, state['base'])
    batch = trainer.synthetic_batch(0)
    state, metrics = trainer.step(state, batch)
    loss_first = float(metrics['loss'])
    for _ in range(5):
        state, metrics = trainer.step(state, batch)
    assert float(metrics['loss']) < loss_first - 0.01
    # The base never moves; only the adapters do.
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(a, np.asarray(b)),
        base_before, state['base'])
    b_leaves = jax.tree.leaves(state['params'])
    assert any(float(jnp.abs(x).max()) > 0 for x in b_leaves)


def test_lora_optimizer_state_is_adapter_sized():
    trainer = _lora_trainer(llama.LLAMA_TINY)
    state = trainer.init_state()
    n_opt = sum(x.size for x in jax.tree.leaves(state['opt_state'])
                if hasattr(x, 'size'))
    n_base = sum(x.size for x in jax.tree.leaves(state['base']))
    assert n_opt < 0.3 * n_base


def test_lora_on_sharded_mesh():
    """Replicated adapters over an fsdp/tensor-sharded frozen base."""
    config = trainer_lib.TrainConfig(
        model=llama.LLAMA_TINY, global_batch_size=4, seq_len=16,
        optimizer='adamw', warmup_steps=1, lora_rank=4,
        learning_rate=1e-2,
        mesh_plan=mesh_lib.MeshPlan(data=2, fsdp=2, tensor=2))
    trainer = trainer_lib.Trainer(config)
    state = trainer.init_state()
    batch = trainer.synthetic_batch(0)
    state, metrics = trainer.step(state, batch)
    loss_first = float(metrics['loss'])
    for _ in range(5):
        state, metrics = trainer.step(state, batch)
    assert float(metrics['loss']) < loss_first - 0.01


def test_lora_works_for_qwen_family():
    trainer = _lora_trainer(qwen.QWEN_TINY)
    state = trainer.init_state()
    batch = trainer.synthetic_batch(0)
    state, m0 = trainer.step(state, batch)
    for _ in range(5):
        state, m = trainer.step(state, batch)
    assert float(m['loss']) < float(m0['loss'])


def test_merged_export_serves_like_trained_model():
    """merged_params produces a plain family tree usable by forward."""
    c = llama.LLAMA_TINY
    trainer = _lora_trainer(c)
    state = trainer.init_state()
    batch = trainer.synthetic_batch(0)
    for _ in range(3):
        state, _ = trainer.step(state, batch)
    merged = lora_lib.merged_params(state['base'], state['params'],
                                    alpha=16.0, rank=4)
    tokens = jnp.zeros((1, 8), jnp.int32)
    out = llama.forward(c, merged, tokens)
    assert out.shape == (1, 8, c.vocab_size)
    # The adapters actually changed the model.
    base_out = llama.forward(c, state['base'], tokens)
    assert float(jnp.abs(out - base_out).max()) > 1e-6
