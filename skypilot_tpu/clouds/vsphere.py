"""vSphere: on-prem vCenter VMs for cross-cloud optimization.

Lean twin of sky/clouds/vsphere.py — VMs cloned from a site-provided
template, priced 0 (BYO capacity, like SSH pools / Kubernetes), so the
optimizer prefers the datacenter when it fits. Instance-type grammar
``cpu-<N>-mem-<GiB>`` resizes the clone; regions are advisory (the
clone lands in the template's cluster).
"""
from __future__ import annotations

import os
import typing
from typing import Any, Dict, Iterator, List, Optional, Tuple

from skypilot_tpu.clouds import cloud as cloud_lib
from skypilot_tpu.utils import registry

if typing.TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib

_PROFILES = ('cpu-2-mem-4', 'cpu-4-mem-8', 'cpu-8-mem-16',
             'cpu-16-mem-64', 'cpu-32-mem-128')


@registry.CLOUD_REGISTRY.register()
class Vsphere(cloud_lib.Cloud):
    _REPR = 'Vsphere'

    @property
    def is_free_capacity(self) -> bool:
        return True  # BYO capacity: $0 means free, rank first

    _UNSUPPORTED = {
        cloud_lib.CloudImplementationFeatures.SPOT_INSTANCE:
            'On-prem VMs have no spot market.',
        cloud_lib.CloudImplementationFeatures.OPEN_PORTS:
            'On-prem networking is site policy.',
        cloud_lib.CloudImplementationFeatures.CUSTOM_DISK_TIER:
            'Clones inherit the template datastore.',
        cloud_lib.CloudImplementationFeatures.STORAGE_MOUNTING:
            'Mount site NFS paths directly instead.',
    }

    @property
    def provisioner_module(self) -> str:
        return 'vsphere'

    def unsupported_features_for_resources(
            self, resources: 'resources_lib.Resources'
    ) -> Dict[cloud_lib.CloudImplementationFeatures, str]:
        return dict(self._UNSUPPORTED)

    def regions_with_offering(self, instance_type: str,
                              accelerators: Optional[Dict[str, Any]],
                              use_spot: bool, region: Optional[str],
                              zone: Optional[str]) -> List[cloud_lib.Region]:
        if use_spot or accelerators:
            return []
        if region not in (None, 'datacenter'):
            return []
        return [cloud_lib.Region('datacenter', ['datacenter'])]

    def zones_provision_loop(self, region: str, num_nodes: int,
                             instance_type: str,
                             accelerators: Optional[Dict[str, Any]] = None,
                             use_spot: bool = False) -> Iterator[List[str]]:
        del region, num_nodes, instance_type, accelerators, use_spot
        yield ['datacenter']

    def get_default_instance_type(
            self, cpus: Optional[str] = None,
            memory: Optional[str] = None) -> Optional[str]:
        want_cpu = float((cpus or '4+').rstrip('+'))
        want_mem = float((memory or '0+').rstrip('+'))
        for profile in _PROFILES:
            parts = profile.split('-')
            if int(parts[1]) >= want_cpu and int(parts[3]) >= want_mem:
                return profile
        return _PROFILES[-1]

    def instance_type_exists(self, instance_type: str) -> bool:
        parts = instance_type.split('-')
        return (len(parts) == 4 and parts[0] == 'cpu' and
                parts[2] == 'mem' and parts[1].isdigit() and
                parts[3].isdigit())

    def get_feasible_launchable_resources(self, resources):
        if resources.accelerators or resources.use_spot:
            return [], []
        itype = resources.instance_type or self.get_default_instance_type(
            resources.cpus, resources.memory)
        if not self.instance_type_exists(itype):
            return [], []
        return [resources.copy(cloud=self.name, instance_type=itype)], []

    def instance_type_to_hourly_cost(self, instance_type: str,
                                     use_spot: bool = False,
                                     region: Optional[str] = None,
                                     zone: Optional[str] = None) -> float:
        return 0.0

    def make_deploy_resources_variables(
            self, resources: 'resources_lib.Resources', cluster_name: str,
            region: str, zone: Optional[str]) -> Dict[str, Any]:
        return {
            'cluster_name': cluster_name,
            'region': 'datacenter',
            'zone': None,
            'instance_type': resources.instance_type,
            'image_id': resources.image_id,
        }

    def provider_config_overrides(
            self, node_config: Dict[str, Any]) -> Dict[str, Any]:
        del node_config
        return {}

    def check_credentials(self) -> Tuple[bool, Optional[str]]:
        from skypilot_tpu.provision.vsphere import rest
        if rest.load_credentials() is not None:
            return True, None
        return False, (
            f'vSphere credentials not found. Populate '
            f'{rest.CREDENTIALS_PATH} with hostname/username/password '
            '(and optionally skip_verification, template_vm).')

    def get_credential_file_mounts(self) -> Dict[str, str]:
        from skypilot_tpu.provision.vsphere import rest
        if os.path.exists(os.path.expanduser(rest.CREDENTIALS_PATH)):
            return {rest.CREDENTIALS_PATH: rest.CREDENTIALS_PATH}
        return {}

    def get_egress_cost(self, num_gigabytes: float) -> float:
        return 0.0
