"""Load tests: a live API server under sustained mixed traffic.

Twin of the reference's tests/load_tests/test_load_on_server.py +
test_queue_dispatcher.py (SURVEY §4.7), bounded so the bucket stays
CI-sized (~20 s): the goal is correctness under concurrency pressure —
no dropped/duplicated requests, bounded latency growth, stable DB —
not absolute throughput numbers.
"""
from __future__ import annotations

import concurrent.futures
import time

import pytest

from skypilot_tpu.client import remote_client
from skypilot_tpu.server import app as server_app
from skypilot_tpu.server import requests_db


@pytest.fixture
def api_server(fake_cluster_env, monkeypatch, tmp_path):
    monkeypatch.setenv('XSKY_SERVER_DB', str(tmp_path / 'requests.db'))
    requests_db.reset_for_test()
    server, port = server_app.run_in_thread()
    yield f'http://127.0.0.1:{port}'
    server.shutdown()
    requests_db.reset_for_test()


def _client(endpoint):
    return remote_client.RemoteClient(endpoint, poll_interval_s=0.02,
                                      timeout_s=120)


class TestServerUnderLoad:

    def test_200_concurrent_short_requests(self, api_server):
        """200 status calls from 32 threads: every one succeeds, and
        the request DB records exactly 200 rows (no drops, no dupes)."""
        def one(_):
            return _client(api_server).status()

        t0 = time.time()
        with concurrent.futures.ThreadPoolExecutor(32) as pool:
            results = list(pool.map(one, range(200)))
        elapsed = time.time() - t0
        assert len(results) == 200
        assert all(isinstance(r, list) for r in results)
        rows = requests_db.list_requests(limit=1000)
        assert len([r for r in rows if r['name'] == 'status']) == 200
        assert all(r['status'] == 'SUCCEEDED' for r in rows)
        # Sanity bound, generous for CI boxes.
        assert elapsed < 60

    def test_mixed_long_and_short_traffic(self, api_server):
        """Launches (long pool) interleaved with status/queue (short
        pool): short requests keep flowing while long ones provision,
        and every request reaches a terminal state."""
        client = _client(api_server)

        def launch(i):
            from skypilot_tpu import Resources, Task
            task = Task(f'load{i}', run='echo hi')
            task.set_resources(Resources(accelerators='tpu-v5e-8'))
            return client.launch(task, cluster_name=f'load-c{i % 4}')

        def short(_):
            return client.status()

        with concurrent.futures.ThreadPoolExecutor(16) as pool:
            longs = [pool.submit(launch, i) for i in range(8)]
            shorts = [pool.submit(short, i) for i in range(60)]
            done_short = [f.result() for f in shorts]
            done_long = [f.result() for f in longs]
        assert len(done_short) == 60
        assert len(done_long) == 8
        rows = requests_db.list_requests(limit=1000)
        assert all(r['status'] in ('SUCCEEDED', 'FAILED')
                   for r in rows)
        # All launches succeeded (4 clusters × 2 jobs each).
        from skypilot_tpu import core
        core_names = {c['name'] for c in core.status()}
        assert {f'load-c{i}' for i in range(4)} <= core_names
        for i in range(4):
            core.down(f'load-c{i}', purge=True)

    def test_large_request_db_listing_stays_fast(self, api_server):
        """A requests DB with 1,000 historical rows must not slow the
        list endpoint or the dashboard's 15-row slice."""
        for i in range(1000):
            rid = requests_db.create('status', f'u{i % 7}', {})
            requests_db.finish(rid, result=[])
        t0 = time.time()
        rows = _client(api_server).list_api_requests(limit=100)
        assert len(rows) == 100
        assert time.time() - t0 < 5
